"""Native C++ tier: build + bit-parity with the pure-Python paths.

The native tier (native/src/{hash,radix,lru}.cc) mirrors the reference's
native-language hot loops (reference: lib/tokens/src/lib.rs hashing;
lib/llm/src/kv_router/indexer.rs radix index;
lib/llm/src/block_manager/pool/inactive.rs pool). These tests build the
library once and then drive both backends with identical randomized
workloads, asserting equal outputs.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from dynamo_tpu import native

pytestmark = pytest.mark.skipif(
    not (native.build() and native.is_available()),
    reason="native tier not buildable in this environment",
)


# ---------------------------------------------------------------------------
# hashing


def test_xxh3_parity_against_python_xxhash():
    import xxhash

    rng = np.random.default_rng(0)
    for n in [0, 1, 3, 8, 16, 17, 63, 64, 65, 128, 129, 240, 241, 1024, 4096]:
        data = rng.bytes(n)
        for seed in [0, 0x4447, 2**63 + 12345]:
            assert native.xxh3_64(data, seed) == xxhash.xxh3_64_intdigest(data, seed=seed)


def test_hash_sequence_parity():
    from dynamo_tpu.tokens import (
        DEFAULT_SALT,
        compute_block_hashes_for_seq,
        compute_seq_hashes,
        hash_sequence,
    )

    rng = np.random.default_rng(1)
    for n_tokens in [0, 5, 16, 17, 160, 1037, 5000]:
        toks = rng.integers(0, 1 << 31, size=n_tokens).astype(np.int32)
        for bs in [1, 16, 64]:
            res = native.hash_sequence(toks, bs, DEFAULT_SALT)
            assert res is not None
            bh, sh = res
            pb = compute_block_hashes_for_seq(toks, bs)
            ps = compute_seq_hashes(pb)
            assert [int(x) for x in bh] == pb
            assert [int(x) for x in sh] == ps
            # the public batch API dispatches to whichever backend is live
            ab, as_ = hash_sequence(toks, bs)
            assert ab == pb and as_ == ps


def test_hash_sequence_high_token_ids():
    # ids in [2^31, 2^32) are valid u32 tokens; the native path must not
    # overflow an int32 conversion and must match the uint32 fallback
    from dynamo_tpu.tokens import compute_block_hashes_for_seq, compute_seq_hashes, hash_sequence

    toks = [2**31 + 5, 2**32 - 1, 7, 0] * 4
    bh, sh = hash_sequence(toks, 4)
    pb = compute_block_hashes_for_seq(toks, 4)
    assert bh == pb and sh == compute_seq_hashes(pb)


def test_chain_hash_parity():
    from dynamo_tpu.tokens import DEFAULT_SALT, chain_hash

    assert native.chain_hash(None, 42, DEFAULT_SALT) == chain_hash(None, 42)
    assert native.chain_hash(7, 42, DEFAULT_SALT) == chain_hash(7, 42)


def test_parallel_hash_path():
    # >64 blocks takes the multithreaded branch; must match exactly
    from dynamo_tpu.tokens import DEFAULT_SALT, compute_block_hashes_for_seq

    rng = np.random.default_rng(2)
    toks = rng.integers(0, 1 << 31, size=16 * 500).astype(np.int32)
    bh, _ = native.hash_sequence(toks, 16, DEFAULT_SALT)
    assert [int(x) for x in bh] == compute_block_hashes_for_seq(toks, 16)


# ---------------------------------------------------------------------------
# radix index


def _random_events(seed: int, n_events: int, n_workers: int, universe: int):
    from dynamo_tpu.kv_router.protocols import KvCacheEvent, RouterEvent

    rnd = random.Random(seed)
    events = []
    for i in range(n_events):
        wid = rnd.randrange(n_workers)
        op = rnd.choices(["stored", "removed", "cleared"], weights=[6, 3, 1])[0]
        hashes = [rnd.randrange(universe) for _ in range(rnd.randrange(1, 8))]
        events.append(
            RouterEvent(
                worker_id=wid,
                event=KvCacheEvent(event_id=i, op=op, block_hashes=hashes),
            )
        )
    return events


def test_radix_parity_randomized():
    from dynamo_tpu.kv_router.indexer import NativeRadixTree, RadixTree

    py, nat = RadixTree(), NativeRadixTree()
    rnd = random.Random(3)
    for ev in _random_events(seed=4, n_events=400, n_workers=5, universe=64):
        py.apply_event(ev)
        nat.apply_event(ev)
        assert nat.num_blocks == py.num_blocks
        if rnd.random() < 0.3:
            # chains walk consecutive hashes; the small universe guarantees hits
            query = [rnd.randrange(64) for _ in range(rnd.randrange(1, 12))]
            a, b = py.find_matches(query), nat.find_matches(query)
            assert a.scores == b.scores
            assert a.total_blocks == b.total_blocks
        if rnd.random() < 0.05:
            wid = rnd.randrange(5)
            py.remove_worker(wid)
            nat.remove_worker(wid)
            assert nat.num_blocks == py.num_blocks


def test_radix_prefix_semantics():
    from dynamo_tpu.kv_router.indexer import NativeRadixTree
    from dynamo_tpu.kv_router.protocols import KvCacheEvent, RouterEvent

    t = NativeRadixTree()
    t.apply_event(
        RouterEvent(worker_id=1, event=KvCacheEvent(event_id=0, op="stored", block_hashes=[10, 11, 12]))
    )
    t.apply_event(
        RouterEvent(worker_id=2, event=KvCacheEvent(event_id=1, op="stored", block_hashes=[10, 11]))
    )
    s = t.find_matches([10, 11, 12, 13])
    assert s.scores == {1: 3, 2: 2}
    assert s.total_blocks == 4
    assert t.workers() == {1, 2}
    t.remove_worker(1)
    assert t.find_matches([10, 11, 12]).scores == {2: 2}


def test_kv_indexer_uses_native():
    from dynamo_tpu.kv_router.indexer import KvIndexer, NativeRadixTree

    idx = KvIndexer(block_size=4)
    assert isinstance(idx.tree, NativeRadixTree)


# ---------------------------------------------------------------------------
# LRU pool index


def test_lru_parity_randomized():
    from dynamo_tpu.kvbm.pool import _PyLruIndex

    py, nat = _PyLruIndex(8), native.NativeLru(8)
    rnd = random.Random(5)
    for step in range(2000):
        r = rnd.random()
        h = rnd.randrange(32)
        if r < 0.5:
            a, b = py.insert(h), nat.insert(h)
            assert a == b, f"step {step}: insert({h}) {a} != {b}"
        elif r < 0.7:
            assert py.lookup(h, touch=True) == nat.lookup(h, touch=True)
        elif r < 0.9:
            assert py.lookup(h, touch=False) == nat.lookup(h, touch=False)
        else:
            assert py.evict(h) == nat.evict(h)
        assert len(py) == len(nat)
        q = [rnd.randrange(32) for _ in range(4)]
        assert py.match_prefix(q) == nat.match_prefix(q)


def test_tier_pool_native_backend_round_trip():
    from dynamo_tpu.kvbm.layout import BlockLayout
    from dynamo_tpu.kvbm.pool import TierPool
    from dynamo_tpu.kvbm.storage import HostBlockStorage

    layout = BlockLayout(
        num_layers=2, block_size=4, num_kv_heads=2, head_dim=8, dtype="float32"
    )
    demoted: list[int] = []
    pool = TierPool(
        HostBlockStorage(layout, 3),
        on_evict=lambda h, data: demoted.append(h),
        use_native=True,
    )
    rng = np.random.default_rng(6)
    blocks = rng.standard_normal((5, *layout.packed_shape)).astype(np.float32)
    for i in range(5):
        pool.insert(100 + i, blocks[i])
    # capacity 3: two oldest were demoted in LRU order
    assert demoted == [100, 101]
    assert pool.num_cached == 3
    got = pool.read([103, 104])
    np.testing.assert_array_equal(got[0], blocks[3])
    np.testing.assert_array_equal(got[1], blocks[4])
    assert pool.match_prefix([102, 103, 999]) == 2


def test_tier_pool_failed_write_rolls_back_index():
    from dynamo_tpu.kvbm.layout import BlockLayout
    from dynamo_tpu.kvbm.pool import TierPool
    from dynamo_tpu.kvbm.storage import HostBlockStorage

    class FlakyStorage(HostBlockStorage):
        fail = False

        def write_blocks(self, ids, data):
            if self.fail:
                raise IOError("disk full")
            super().write_blocks(ids, data)

    layout = BlockLayout(
        num_layers=1, block_size=2, num_kv_heads=1, head_dim=4, dtype="float32"
    )
    storage = FlakyStorage(layout, 2)
    pool = TierPool(storage)
    ok = np.ones(layout.packed_shape, np.float32)
    pool.insert(1, ok)
    storage.fail = True
    with pytest.raises(IOError):
        pool.insert(2, ok)
    # the failed hash must not be readable (stale bytes) afterwards
    assert not pool.contains(2)
    assert pool.num_cached == 1
    storage.fail = False
    pool.insert(2, ok)
    assert pool.contains(2)
