"""Native (C++) coordinator parity: the python StoreClient and the full
distributed runtime must behave identically over native/store/
store_server.cc as over the python StoreServer (which is the semantic
reference)."""

import asyncio
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(REPO, "dynamo_tpu", "native", "dynamo_store")


@pytest.fixture(scope="module")
def native_store_binary():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "native", "build.py")],
        capture_output=True, text=True,
    )
    if not os.path.exists(BINARY):
        pytest.skip(f"native store build unavailable: {r.stderr[-200:]}")
    return BINARY  # build.py also produced libdynamo_kv.so


@pytest.fixture
def native_store(native_store_binary):
    # sync fixture: the conftest's asyncio shim only handles async TESTS
    proc = subprocess.Popen(
        [native_store_binary, "--host", "127.0.0.1", "--port", "0"],
        stdout=subprocess.PIPE,
    )
    line = proc.stdout.readline()
    assert line.startswith(b"LISTENING"), line
    port = int(line.split()[1])
    yield port
    proc.kill()
    proc.wait()


async def test_native_store_full_parity(native_store):
    from dynamo_tpu.store.client import StoreClient

    c = await StoreClient.connect("127.0.0.1", native_store)
    try:
        # kv: versions, create, prefix order, delete
        v1 = await c.kv_put("a/x", b"1")
        v2 = await c.kv_put("a/y", b"2")
        assert v2 > v1
        assert not await c.kv_create("a/x", b"dupe")
        assert await c.kv_create("a/new", b"n")
        got = await c.kv_get_prefix("a/")
        assert [e.key for e in got] == ["a/new", "a/x", "a/y"]
        assert await c.kv_delete("a/new")
        assert not await c.kv_delete("a/new")
        assert await c.kv_delete_prefix("a/") == 2

        # lease against a missing id errors like the python server
        with pytest.raises(Exception):
            await c.kv_put("k", b"v", lease_id=424242)

        # watch: snapshot + put/delete events
        await c.kv_put("w/1", b"a")
        w = await c.watch_prefix("w/")
        assert [e.key for e in w.snapshot()] == ["w/1"]
        await c.kv_put("w/2", b"b")
        await c.kv_delete("w/1")
        it = w.__aiter__()
        ev1 = await asyncio.wait_for(it.__anext__(), 3)
        ev2 = await asyncio.wait_for(it.__anext__(), 3)
        assert (ev1.type, ev1.entry.key) == ("put", "w/2")
        assert (ev2.type, ev2.entry.key) == ("delete", "w/1")
        await w.close()

        # lease expiry deletes attached keys
        lid = await c.lease_grant(0.3)
        await c.kv_put("lease/me", b"x", lease_id=lid)
        await asyncio.sleep(0.8)
        assert await c.kv_get("lease/me") is None

        # re-put under a new lease detaches from the old one
        l1 = await c.lease_grant(0.3)
        l2 = await c.lease_grant(30)
        await c.kv_put("stable", b"1", lease_id=l1)
        await c.kv_put("stable", b"2", lease_id=l2)
        await asyncio.sleep(0.8)  # l1 expires: must NOT delete "stable"
        e = await c.kv_get("stable")
        assert e is not None and e.value == b"2"

        # pub/sub wildcards
        sub = await c.subscribe("ns.*.ev")
        subj_all = await c.subscribe("ns.>")
        await c.publish("ns.w1.ev", b"p1")
        await c.publish("other.w1.ev", b"nope")
        s, p = await asyncio.wait_for(sub.__aiter__().__anext__(), 3)
        assert (s, p) == ("ns.w1.ev", b"p1")
        s2, _ = await asyncio.wait_for(subj_all.__aiter__().__anext__(), 3)
        assert s2 == "ns.w1.ev"
        await sub.close()
        await subj_all.close()

        # queues: fifo, blocking pop, visibility redelivery, ack, len
        await c.queue_push("q", b"m1")
        await c.queue_push("q", b"m2")
        m1 = await c.queue_pop("q", timeout_s=1, visibility_s=30)
        m2 = await c.queue_pop("q", timeout_s=1, visibility_s=0.3)
        assert (m1.payload, m2.payload) == (b"m1", b"m2")
        assert await c.queue_ack("q", m1.id)
        await asyncio.sleep(0.8)  # m2 visibility expires -> redelivered
        m2b = await c.queue_pop("q", timeout_s=2)
        assert m2b.payload == b"m2"
        assert await c.queue_ack("q", m2b.id)
        assert not await c.queue_ack("q", m2b.id)
        assert await c.queue_len("q") == 0
        assert await c.queue_pop("q", timeout_s=0.1) is None

        # object plane (binary-safe)
        blob = bytes(range(256)) * 10
        await c.obj_put("bkt", "blob", blob)
        assert await c.obj_get("bkt", "blob") == blob
        assert await c.obj_list("bkt") == ["blob"]
        assert await c.obj_delete("bkt", "blob")
        assert await c.obj_get("bkt", "blob") is None
    finally:
        await c.close()


async def test_runtime_e2e_over_native_store(native_store):
    """The full distributed runtime (serve + discovery + streaming call +
    lease liveness) over the C++ coordinator."""
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.engine import Context, FnEngine, collect
    from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
    from dynamo_tpu.runtime.runtime import DistributedRuntime

    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_host="127.0.0.1", store_port=native_store,
        worker_host="127.0.0.1", lease_ttl_s=1.0, lease_keepalive_s=0.3,
    )

    async def echo(request, ctx):
        for tok in request["tokens"]:
            yield {"token": tok}

    worker = await DistributedRuntime.create(config=cfg())
    frontend = await DistributedRuntime.create(config=cfg())
    try:
        ep = worker.namespace("cns").component("w").endpoint("gen")
        await ep.serve(FnEngine(echo))
        client = await (
            frontend.namespace("cns").component("w").endpoint("gen").client()
        )
        await client.wait_for_instances()
        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        items = await collect(router.generate({"tokens": [1, 2, 3]}, Context()))
        assert [i["token"] for i in items] == [1, 2, 3]

        # worker death (connection drop) revokes its lease: the instance
        # disappears from discovery within the sweep interval
        await worker.shutdown()
        for _ in range(40):
            if not client.instance_ids():
                break
            await asyncio.sleep(0.1)
        assert not client.instance_ids()
    finally:
        await frontend.shutdown()


KV_LIB = os.path.join(REPO, "dynamo_tpu", "native", "libdynamo_kv.so")


async def _drive_c_publisher(port: int) -> None:
    """Publish from the C ABI to the given coordinator port and assert
    the python subscriber receives valid RouterEvents — including hashes
    with the top bit set (must arrive as UNSIGNED ints, matching the
    radix tree's xxh3 keys)."""
    import ctypes

    import msgpack

    from dynamo_tpu.kv_router.protocols import RouterEvent
    from dynamo_tpu.store.client import StoreClient

    big = 0x9000000000000001  # >= 2^63: a signed-int64 encoding would corrupt it
    client = await StoreClient.connect("127.0.0.1", port)
    sub = await client.subscribe("ns.backend.kv_events")
    try:
        lib = ctypes.CDLL(KV_LIB)
        lib.dynamo_kv_publisher_connect.restype = ctypes.c_void_p
        lib.dynamo_kv_publisher_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_longlong, ctypes.c_int,
        ]
        lib.dynamo_kv_publisher_publish.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_ulonglong), ctypes.c_int,
        ]

        def publish():
            h = lib.dynamo_kv_publisher_connect(
                b"127.0.0.1", port, b"ns.backend.kv_events", 42, 16
            )
            assert h
            arr = (ctypes.c_ulonglong * 3)(111, big, 333)
            assert lib.dynamo_kv_publisher_publish(h, b"stored", arr, 3) == 0
            assert lib.dynamo_kv_publisher_publish(h, b"removed", arr, 1) == 0
            assert lib.dynamo_kv_publisher_publish(h, b"stored", None, 1) == -1
            lib.dynamo_kv_publisher_close(ctypes.c_void_p(h))

        await asyncio.get_running_loop().run_in_executor(None, publish)
        events = []

        async def consume():
            async for _subj, payload in sub:
                events.append(
                    RouterEvent.model_validate(msgpack.unpackb(payload, raw=False))
                )
                if len(events) == 2:
                    return

        await asyncio.wait_for(consume(), 5)
        assert events[0].worker_id == 42
        assert events[0].event.op == "stored"
        assert events[0].event.block_hashes == [111, big, 333]
        assert events[0].event.token_block_size == 16
        assert events[1].event.op == "removed"
        assert [e.event_id for e in events] == [1, 2]
    finally:
        await sub.close()
        await client.close()


async def test_c_abi_kv_publisher_python_server(native_store_binary):
    """C publisher against the python StoreServer."""
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    if not os.path.exists(KV_LIB):
        pytest.skip("kv publisher lib unavailable")
    server = StoreServer(MemoryStore(), port=0)
    await server.start()
    try:
        await _drive_c_publisher(server.port)
    finally:
        await server.stop()


async def test_c_abi_kv_publisher_native_server(native_store):
    """The no-python-in-the-path pairing: C publisher -> C++ coordinator."""
    if not os.path.exists(KV_LIB):
        pytest.skip("kv publisher lib unavailable")
    await _drive_c_publisher(native_store)


async def test_parked_pop_survives_client_disconnect(native_store):
    """A client that parks a blocking queue_pop and then disconnects must
    not leave the server holding a dangling Conn*: the next queue_push (and
    the sweep tick) previously dereferenced the freed connection. The
    message must be redelivered intact to a live consumer."""
    from dynamo_tpu.store.client import StoreClient

    victim = await StoreClient.connect("127.0.0.1", native_store)
    await victim.queue_len("uaf")  # ensure the queue exists server-side
    # park a long blocking pop, then drop the connection without unparking
    pop_task = asyncio.ensure_future(victim.queue_pop("uaf", timeout_s=30))
    await asyncio.sleep(0.3)  # let the pop reach the server and park
    await victim.close()
    with pytest.raises((ConnectionError, asyncio.CancelledError)):
        await pop_task

    c = await StoreClient.connect("127.0.0.1", native_store)
    try:
        # push triggers serve_parked() over the dead conn's parked entry
        await c.queue_push("uaf", b"survivor")
        await asyncio.sleep(0.3)  # span at least one sweep tick as well
        # the server must still be alive and must not have delivered the
        # message into the void: a live pop gets it
        m = await c.queue_pop("uaf", timeout_s=3)
        assert m is not None and m.payload == b"survivor"
        assert await c.queue_ack("uaf", m.id)
        # plain liveness probe after the dust settles
        assert await c.kv_put("uaf/alive", b"1") > 0
    finally:
        await c.close()


async def test_native_codec_randomized_roundtrip(native_store):
    """Property-style cross-implementation check (≈ the reference's
    proptest protocol validation): random keys/values — every bin length
    0..1KB, embedded NULs, high-bit bytes, unicode keys — must round-trip
    python-msgpack -> C++ decoder -> C++ encoder -> python-msgpack
    byte-identically through the native server's kv plane."""
    import random

    from dynamo_tpu.store.client import StoreClient

    rng = random.Random(0xD1CE)
    c = await StoreClient.connect("127.0.0.1", native_store)
    try:
        cases = []
        for i in range(120):
            key = f"fz/{i:03d}-" + "".join(
                rng.choice("abcxyz日本語🙂/._-") for _ in range(rng.randrange(0, 12))
            )
            value = bytes(rng.randrange(256) for _ in range(rng.randrange(0, 1024)))
            cases.append((key, value))
        versions = {}
        for key, value in cases:
            versions[key] = await c.kv_put(key, value)
        for key, value in cases:
            e = await c.kv_get(key)
            assert e is not None and e.value == value, key
            assert e.version == versions[key]
        listed = await c.kv_get_prefix("fz/")
        assert len(listed) == len({k for k, _ in cases})
        assert [e.key for e in listed] == sorted({k for k, _ in cases})
        # object plane: a large binary blob with every byte value
        blob = bytes(range(256)) * 512  # 128 KiB
        await c.obj_put("fz", "blob", blob)
        assert await c.obj_get("fz", "blob") == blob
    finally:
        await c.close()


async def test_native_store_wal_survives_kill9(native_store_binary, tmp_path):
    """Durability parity with the python store (VERDICT r3 item 7): the
    native server WALs every acked mutation, so a kill -9 UNDER TRAFFIC
    (no SIGTERM snapshot, no 2s tick grace) loses nothing acked — KV,
    unacked queue messages (including in-flight, redelivered as ready),
    and the object plane all survive; acked messages never redeliver.
    Reference role: etcd raft log / JetStream file store
    (lib/runtime/src/transports/{etcd,nats}.rs)."""
    import signal

    from dynamo_tpu.store.client import StoreClient

    persist = str(tmp_path / "store.bin")

    def start():
        proc = subprocess.Popen(
            [native_store_binary, "--host", "127.0.0.1", "--port", "0",
             "--persist-path", persist],
            stdout=subprocess.PIPE,
        )
        line = proc.stdout.readline()
        assert line.startswith(b"LISTENING"), line
        return proc, int(line.split()[1])

    proc, port = start()
    try:
        c = await StoreClient.connect("127.0.0.1", port)
        await c.kv_put("model/reg", b"card-v1")
        await c.kv_put("model/other", b"x")
        await c.kv_delete("model/other")
        lid = await c.lease_grant(30.0)
        await c.kv_put("live/worker", b"ephemeral", lease_id=lid)
        for i in range(4):
            await c.queue_push("prefill", f"job-{i}".encode())
        # job-0 popped+acked (must NOT come back), job-1 popped but
        # UNACKED (in-flight at the kill: must come back ready)
        m0 = await c.queue_pop("prefill", timeout_s=1)
        assert m0.payload == b"job-0"
        assert await c.queue_ack("prefill", m0.id)
        m1 = await c.queue_pop("prefill", timeout_s=1)
        assert m1.payload == b"job-1"
        await c.obj_put("artifacts", "tok.json", b"{}")
        await c.close()
    finally:
        # hard kill: no SIGTERM handler, no final snapshot
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    proc, port = start()
    try:
        c = await StoreClient.connect("127.0.0.1", port)
        e = await c.kv_get("model/reg")
        assert e is not None and e.value == b"card-v1"
        assert await c.kv_get("model/other") is None
        # leased liveness key is ephemeral by design
        assert await c.kv_get("live/worker") is None
        # in-flight job-1 redelivers; job-2/3 still queued; job-0 never
        seen = []
        for _ in range(3):
            m = await c.queue_pop("prefill", timeout_s=1)
            assert m is not None
            seen.append(m.payload)
            await c.queue_ack("prefill", m.id)
        assert sorted(seen) == [b"job-1", b"job-2", b"job-3"]
        assert await c.queue_pop("prefill", timeout_s=0) is None
        assert await c.obj_get("artifacts", "tok.json") == b"{}"
        # new pushes must not collide with pre-crash message ids
        nid = await c.queue_push("prefill", b"post-crash")
        assert nid > m1.id
        await c.close()
    finally:
        proc.kill()
        proc.wait()


async def test_native_store_wal_compaction_no_double_delivery(
    native_store_binary, tmp_path
):
    """A snapshot (2s tick) folds WAL records in and truncates the log;
    messages folded into the snapshot must not ALSO replay from any
    surviving WAL records after a later crash."""
    import signal

    from dynamo_tpu.store.client import StoreClient

    persist = str(tmp_path / "store.bin")
    proc = subprocess.Popen(
        [native_store_binary, "--host", "127.0.0.1", "--port", "0",
         "--persist-path", persist],
        stdout=subprocess.PIPE,
    )
    line = proc.stdout.readline()
    port = int(line.split()[1])
    try:
        c = await StoreClient.connect("127.0.0.1", port)
        await c.queue_push("q", b"early")
        await asyncio.sleep(2.5)  # let the snapshot tick fold + truncate
        await c.queue_push("q", b"late")  # lands in the fresh WAL
        await c.close()
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    proc = subprocess.Popen(
        [native_store_binary, "--host", "127.0.0.1", "--port", "0",
         "--persist-path", persist],
        stdout=subprocess.PIPE,
    )
    line = proc.stdout.readline()
    port = int(line.split()[1])
    try:
        c = await StoreClient.connect("127.0.0.1", port)
        got = []
        while True:
            m = await c.queue_pop("q", timeout_s=0)
            if m is None:
                break
            got.append(m.payload)
            await c.queue_ack("q", m.id)
        assert sorted(got) == [b"early", b"late"]
        await c.close()
    finally:
        proc.kill()
        proc.wait()
