"""Live introspection + performance attribution (ISSUE 4): flight
recorder ring/dump semantics, SLO attainment math, HBM accounting
fallback, the /debug/state + /debug/profile endpoints, the
`dynamo-tpu top` fleet view, and the e2e acceptance path — a slow
request produces a JSONL flight dump whose offending step carries
per-phase latency, while /debug/state and /metrics agree on KV-pool
occupancy for the same moment."""

import asyncio
import io
import json
import os
import time

import aiohttp
import pytest

from dynamo_tpu.telemetry import debug as tdebug
from dynamo_tpu.telemetry.hbm import HbmAccountant, tree_bytes
from dynamo_tpu.telemetry.recorder import FlightRecorder
from dynamo_tpu.telemetry.slo import SloConfig, SloTracker

from tests.prom_parser import parse as prom_parse

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_flight_recorder_ring_is_bounded():
    rec = FlightRecorder(capacity=8)
    for i in range(50):
        rec.record("decode", 0.001, batch=i)
    snap = rec.snapshot(100)
    assert len(snap) == 8  # deque(maxlen=8): old entries fell off
    assert [r["batch"] for r in snap] == list(range(42, 50))
    assert rec.steps_recorded == 50


def test_flight_recorder_slow_step_dumps(tmp_path):
    rec = FlightRecorder(
        capacity=16, slow_step_s=0.010, dump_dir=str(tmp_path),
        min_dump_interval_s=0.0,
    )
    for _ in range(5):
        assert rec.record("decode", 0.001, batch=4) is None  # under threshold
    path = rec.record(
        "prefill", 0.050, batch=2, dispatch_ms=48.0, sync_ms=1.5,
        plan_ms=0.3,
    )
    assert path is not None and os.path.exists(path)
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    header, records = lines[0], lines[1:]
    assert header["flight_recorder_dump"] is True
    assert header["reason"] == "slow_step:prefill"
    assert len(records) == 6
    slow = [r for r in records if r.get("slow")]
    assert len(slow) == 1
    # the offending step carries its per-phase latency breakdown
    assert slow[0]["kind"] == "prefill"
    assert slow[0]["duration_ms"] == pytest.approx(50.0)
    assert slow[0]["dispatch_ms"] == 48.0
    assert slow[0]["sync_ms"] == 1.5
    assert slow[0]["plan_ms"] == 0.3
    assert slow[0]["slow_threshold_ms"] == pytest.approx(10.0)


def test_flight_recorder_dumps_are_rate_limited(tmp_path):
    now = [0.0]
    rec = FlightRecorder(
        capacity=4, slow_step_s=0.001, dump_dir=str(tmp_path),
        min_dump_interval_s=30.0, clock=lambda: now[0],
    )
    assert rec.record("decode", 0.5) is not None
    assert rec.record("decode", 0.5) is None  # suppressed: too soon
    assert rec.slow_steps == 2  # still counted as slow
    now[0] = 31.0
    assert rec.record("decode", 0.5) is not None  # window elapsed
    assert rec.dumps_written == 2


def test_flight_recorder_failed_dump_does_not_arm_rate_limit(tmp_path):
    rec = FlightRecorder(
        capacity=4, slow_step_s=0.001,
        dump_dir=os.path.join(str(tmp_path), "missing", "dir"),
        min_dump_interval_s=3600.0,
    )
    assert rec.record("decode", 0.5) is None  # write failed (no dir)
    rec.dump_dir = str(tmp_path)
    # a failed dump persisted nothing, so the next trigger must not be
    # suppressed by the rate limiter
    assert rec.record("decode", 0.5) is not None


def test_flight_recorder_caps_on_disk_dump_files(tmp_path):
    rec = FlightRecorder(
        capacity=4, slow_step_s=0.001, dump_dir=str(tmp_path),
        min_dump_interval_s=0.0, max_dump_files=3,
    )
    paths = [rec.record("decode", 0.5) for _ in range(5)]
    assert all(paths)
    on_disk = sorted(
        p for p in os.listdir(tmp_path) if p.startswith("dynamo_flight_")
    )
    # dumps 1 and 2 were unlinked when 4 and 5 landed: disk is bounded
    assert len(on_disk) == 3
    assert on_disk == [os.path.basename(p) for p in paths[-3:]]


def test_flight_recorder_slow_request_dump(tmp_path):
    rec = FlightRecorder(
        capacity=8, dump_dir=str(tmp_path), min_dump_interval_s=0.0,
    )
    rec.record("decode", 0.001)
    path = rec.note_slow_request("req-9", ttft_ms=812.0, tokens=30)
    assert path is not None
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert lines[0]["reason"] == "slow_request:req-9"
    marker = [r for r in lines[1:] if r.get("kind") == "slow_request"]
    assert marker and marker[0]["request_id"] == "req-9"
    assert marker[0]["ttft_ms"] == 812.0


# ---------------------------------------------------------------------------
# SLO attainment / goodput math
# ---------------------------------------------------------------------------
def test_slo_attainment_math():
    t = SloTracker(SloConfig(ttft_ms=100.0, itl_ms=10.0), window=16)
    assert t.attainment == 1.0  # nothing observed yet
    assert t.observe(0.050, 0.005, completion_tokens=10) is True
    assert t.observe(0.200, 0.005, completion_tokens=10) is False  # ttft miss
    assert t.observe(0.050, 0.020, completion_tokens=10) is False  # itl miss
    assert t.observe(0.050, None, completion_tokens=5) is True  # no itl: n/a
    assert t.attainment == pytest.approx(2 / 4)
    assert t.goodput_tokens == 15  # only SLO-met requests count
    s = t.stats()
    assert s["requests_seen"] == 4 and s["requests_met"] == 2
    assert s["targets"] == {"ttft_ms": 100.0, "itl_ms": 10.0}


def test_slo_rolling_window_forgets_old_outcomes():
    t = SloTracker(SloConfig(ttft_ms=100.0), window=4)
    for _ in range(4):
        t.observe(1.0, None)  # all miss
    assert t.attainment == 0.0
    for _ in range(4):
        t.observe(0.01, None)  # all meet: misses roll out of the window
    assert t.attainment == 1.0


def test_aggregate_slo_shared_rollup():
    from dynamo_tpu.telemetry.slo import aggregate_slo

    class W:
        def __init__(self, enabled, attain, goodput):
            self.slo_enabled = enabled
            self.slo_attainment = attain
            self.goodput_tokens_total = goodput

    attainment, goodput = aggregate_slo([
        W(True, 0.5, 100), W(True, 1.0, 300),
        W(False, 1.0, 0),  # target-less: excluded from the mean
    ])
    assert attainment == 0.75 and goodput == 400
    assert aggregate_slo([]) == (1.0, 0.0)
    assert aggregate_slo([W(False, 1.0, 50)]) == (1.0, 50.0)


async def test_errored_requests_do_not_score_slo(tmp_path):
    """ERROR finishes must not count as goodput or attainment: a fleet
    in an error loop reporting 'healthy' would invert the Planner
    signal."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(
        slo_ttft_ms=60_000.0, flight_dump_dir=str(tmp_path),
    ))
    try:
        await engine.wait_for_state(lambda e: e.scheduler is not None)

        def always_boom(*a, **kw):
            raise RuntimeError("persistent failure")

        engine._run_device_step = always_boom
        engine._dispatch_mixed = always_boom
        engine._dispatch_multi_step = always_boom
        out = await _gen(engine, range(1, 12), request_id="err")
        assert out == []
        assert engine.slo.requests_seen == 0
        assert engine.slo.goodput_tokens == 0
        assert engine.slo.attainment == 1.0
    finally:
        await engine.shutdown()


def test_slo_disabled_records_but_does_not_score():
    t = SloTracker(SloConfig())
    assert not t.config.enabled
    assert t.observe(99.0, 99.0, completion_tokens=100) is True
    assert t.attainment == 1.0
    assert t.goodput_tokens == 0
    assert t.requests_seen == 0


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------
def test_hbm_accountant_portable_fallback():
    acct = HbmAccountant(device=None)
    acct.set_static(weight_bytes=1000, kv_pool_bytes=500)
    snap = acct.refresh()
    assert snap["source"] == "accounted"
    assert snap["weight_bytes"] == 1000
    assert snap["kv_pool_bytes"] == 500
    assert snap["bytes_in_use"] == 1500
    assert snap["peak_bytes_in_use"] == 1500
    acct.set_static(weight_bytes=100, kv_pool_bytes=50)
    snap2 = acct.refresh()
    assert snap2["bytes_in_use"] == 150
    assert snap2["peak_bytes_in_use"] == 1500  # watermark held


def test_tree_bytes_counts_nested_arrays():
    import numpy as np

    tree = {"a": np.zeros((4, 4), np.float32),
            "kv": (np.zeros(8, np.int8), np.zeros(2, np.float32))}
    assert tree_bytes(tree) == 64 + 8 + 8


# ---------------------------------------------------------------------------
# debug provider registry
# ---------------------------------------------------------------------------
def test_debug_provider_registry_isolation():
    def good():
        return {"x": 1}

    def bad():
        raise RuntimeError("torn read")

    tdebug.register_debug_provider("t_good", good)
    tdebug.register_debug_provider("t_bad", bad)
    try:
        state = tdebug.collect_debug_state()
        assert state["t_good"] == {"x": 1}
        # a raising provider degrades to an error stanza, not a crash
        assert "RuntimeError" in state["t_bad"]["error"]
        assert "ts" in state and "pid" in state
    finally:
        tdebug.unregister_debug_provider("t_good")
        tdebug.unregister_debug_provider("t_bad")
    assert "t_good" not in tdebug.debug_provider_names()


def test_debug_provider_unregister_checks_identity():
    tdebug.register_debug_provider("t_ident", lambda: {"v": 2})
    try:
        # a DIFFERENT provider under the same name must not be yanked
        tdebug.unregister_debug_provider("t_ident", lambda: {"v": 3})
        assert "t_ident" in tdebug.debug_provider_names()
    finally:
        tdebug.unregister_debug_provider("t_ident")


# ---------------------------------------------------------------------------
# /debug endpoints on the HTTP frontend
# ---------------------------------------------------------------------------
async def _start_frontend():
    from dynamo_tpu.http.service import HttpService, ModelManager

    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}"


async def test_debug_state_endpoint_schema():
    tdebug.register_debug_provider("t_worker", lambda: {"busy": True})
    service, base = await _start_frontend()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/state") as r:
                assert r.status == 200
                state = await r.json()
        assert state["t_worker"] == {"busy": True}
        assert state["frontend"]["models"] == []
        assert state["frontend"]["port"] == service.port
    finally:
        tdebug.unregister_debug_provider("t_worker")
        await service.stop()


async def test_debug_profile_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("DYN_PROFILE_DIR", str(tmp_path))
    service, base = await _start_frontend()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/profile?ms=50") as r:
                assert r.status == 200, await r.text()
                body = await r.json()
            assert body["duration_ms"] == 50
            assert os.path.isdir(body["trace_dir"])
            assert body["trace_dir"].startswith(str(tmp_path))
            async with s.get(f"{base}/debug/profile?ms=nope") as r:
                assert r.status == 400
    finally:
        await service.stop()


# ---------------------------------------------------------------------------
# e2e acceptance: slow request -> flight dump; /debug/state vs /metrics
# ---------------------------------------------------------------------------
def _engine_cfg(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    defaults = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=128, block_size=8, max_batch_size=8,
        prefill_chunk_size=32, max_model_len=256,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _gen(engine, prompt, max_tokens=8, request_id="r"):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        request_id=request_id, token_ids=list(prompt),
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens),
    )
    out = []
    async for item in engine.as_async_engine().generate(req, Context()):
        out.extend(item.token_ids)
    return out


async def test_e2e_slow_step_dump_and_consistent_kv_occupancy(tmp_path):
    """The acceptance bar: an injected device-step delay trips the
    slow-step watchdog, the dump contains the offending step WITH its
    per-phase latency, and /debug/state + /metrics agree on KV-pool
    occupancy for the same moment."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(
        slow_step_ms=40.0,
        flight_dump_dir=str(tmp_path),
        slo_ttft_ms=10_000.0,  # generous: CPU test backend
    ))
    service = None
    try:
        # inject a delay into every synced device step
        orig = engine._run_device_step

        def slow_step(arrays, sampling, **kw):
            time.sleep(0.08)
            return orig(arrays, sampling, **kw)

        engine._run_device_step = slow_step
        toks = await _gen(engine, range(1, 20), request_id="slowreq")
        assert len(toks) == 8
        engine._run_device_step = orig

        # -- flight dump: offending step + per-phase latency ------------
        dumps = sorted(
            p for p in os.listdir(tmp_path) if p.startswith("dynamo_flight_")
        )
        assert dumps, "slow steps produced no flight-recorder dump"
        lines = [
            json.loads(x)
            for x in open(os.path.join(tmp_path, dumps[0])).read().splitlines()
        ]
        assert lines[0]["reason"].startswith("slow_step:")
        slow_recs = [r for r in lines[1:] if r.get("slow")]
        assert slow_recs, "dump lacks the offending step"
        off = slow_recs[0]
        assert off["duration_ms"] > 40.0
        assert "dispatch_ms" in off  # per-phase latency present
        assert "plan_ms" in off
        assert off["queue_depth"] >= 0 and "batch" in off

        # -- /debug/state vs /metrics occupancy -------------------------
        service, base = await _start_frontend()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/state") as r:
                assert r.status == 200
                state = await r.json()
            async with s.get(f"{base}/metrics") as r:
                metrics_text = await r.text()
        eng = state["engine"]
        pool = eng["kv_pool"]
        fams = prom_parse(metrics_text)
        active = fams["dynamo_kv_pool_blocks_active"].samples[
            ("dynamo_kv_pool_blocks_active", ())
        ]
        total = fams["dynamo_kv_pool_blocks_total"].samples[
            ("dynamo_kv_pool_blocks_total", ())
        ]
        assert pool["active_blocks"] == active
        assert pool["total_blocks"] == total == 127
        assert pool["active_blocks"] + pool["free_blocks"] == total
        # the engine snapshot carries the rest of the introspection
        # surface the CLI renders
        assert eng["scheduler"]["running"] == 0
        assert eng["hbm"]["kv_pool_bytes"] > 0
        assert eng["slo"]["enabled"] is True
        assert eng["slo"]["requests_seen"] >= 1
        assert eng["recent_steps"], "flight recorder tail missing"
        assert eng["load"]["goodput_tokens_total"] >= 8
        # SLO histograms made it into the exposition machinery
        assert fams["dynamo_request_ttft_seconds"].type == "histogram"
        assert fams["dynamo_slo_attainment"].samples[
            ("dynamo_slo_attainment", ())
        ] == 1.0
    finally:
        if service is not None:
            await service.stop()
        await engine.shutdown()


async def test_slo_miss_scores_and_dumps(tmp_path):
    """An impossible ITL target: the request misses, attainment drops,
    and the request watchdog dumps the ring."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(
        slo_ttft_ms=100_000.0, slo_itl_ms=0.0001,
        flight_dump_dir=str(tmp_path),
    ))
    try:
        await _gen(engine, range(1, 16), request_id="misser")
        assert engine.slo.attainment < 1.0
        assert engine.slo.goodput_tokens == 0
        dumps = [
            p for p in os.listdir(tmp_path) if p.startswith("dynamo_flight_")
        ]
        assert dumps, "SLO miss did not trip the request watchdog"
    finally:
        await engine.shutdown()


# ---------------------------------------------------------------------------
# dynamo-tpu top
# ---------------------------------------------------------------------------
async def test_top_renders_fleet_frame():
    from dynamo_tpu.cli.top import run_top

    tokens = [1000]

    def fake_engine():
        tokens[0] += 500
        return {
            "model": "tiny-model",
            "max_batch_size": 8,
            "tokens_generated_total": tokens[0],
            "scheduler": {"running": 3, "queue_depth": 2, "preemptions": 1},
            "kv_pool": {"usage": 0.25, "active_blocks": 32,
                        "total_blocks": 128},
            "slo": {"enabled": True, "attainment": 0.875},
            "hbm": {"bytes_in_use": 2 * 1024 * 1024},
            "flight_recorder": {"slow_steps": 4},
            "load": {"goodput_tokens_total": 0},  # no SLO targets: tok/s
            # must NOT come from goodput
        }

    tdebug.register_debug_provider("engine", fake_engine)
    service, base = await _start_frontend()
    try:
        buf = io.StringIO()
        rc = await run_top([base], interval=0.01, iterations=2,
                           clear=False, out=buf)
        assert rc == 0
        text = buf.getvalue()
        assert "WORKER" in text and "tiny-model" in text
        assert "25.0%" in text  # kv usage
        assert "87.5%" in text  # slo attainment
        assert "2.0MB" in text  # hbm
        # second frame derives a NONZERO rate from generated-token
        # deltas even though goodput is 0 (no SLO targets configured)
        frames = text.split("dynamo-tpu top")
        assert "       -" in frames[1]  # first frame: no delta yet
        import re

        rates = re.findall(r" (\d+\.\d)\b", frames[2])
        assert any(float(x) > 0 for x in rates), frames[2]
    finally:
        tdebug.unregister_debug_provider("engine")
        await service.stop()


async def test_top_raw_mode_and_dead_worker():
    from dynamo_tpu.cli.top import run_top

    buf = io.StringIO()
    # unroutable port: every worker erroring is exit code 1
    rc = await run_top(["http://127.0.0.1:1"], interval=0.01,
                       iterations=1, raw=True, out=buf)
    assert rc == 1
    row = json.loads(buf.getvalue())
    assert "error" in row["http://127.0.0.1:1"]


def test_top_cli_parser_wiring():
    from dynamo_tpu.cli.main import build_parser

    args = build_parser().parse_args(
        ["top", "http://h:1", "--once", "--raw", "--interval", "0.5"]
    )
    assert args.command == "top"
    assert args.urls == ["http://h:1"]
    assert args.once and args.raw and args.interval == 0.5
    run_args = build_parser().parse_args(
        ["run", "--slo-ttft-ms", "500", "--slo-itl-ms", "40",
         "--slow-step-ms", "250", "--flight-recorder-steps", "128"]
    )
    assert run_args.slo_ttft_ms == 500.0
    assert run_args.slo_itl_ms == 40.0
    assert run_args.slow_step_ms == 250.0
    assert run_args.flight_recorder_steps == 128
    from dynamo_tpu.engine.config import load_engine_config

    cfg = load_engine_config(run_args)
    assert cfg.slo_ttft_ms == 500.0 and cfg.slow_step_ms == 250.0
    assert cfg.flight_recorder_steps == 128


# ---------------------------------------------------------------------------
# perf attribution: ledger units (telemetry/attribution.py)
# ---------------------------------------------------------------------------
def _ledger(**kw):
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.telemetry.attribution import AttributionLedger
    from dynamo_tpu.telemetry.roofline import build_roofline

    mc = ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32,
        num_key_value_heads=8, max_position_embeddings=8192,
    )
    return AttributionLedger(build_roofline(mc, "int8", "int8"), **kw)


def _tick(dt=0.01):
    now = [0.0]

    def clock():
        now[0] += dt
        return now[0]

    return clock


def test_ledger_partition_sums_to_wall_time():
    led = _ledger(clock=_tick(0.010))
    for _ in range(64):
        led.note_step(
            "decode", 0.010, batch=64, tokens=64, context_tokens=64 * 192,
            plan_ms=2.0, dispatch_ms=1.0, sync_ms=0.5, idle_gap_ms=3.0,
            overlapped=True,
        )
    w = led.window_summary()
    assert sum(w["frac"].values()) == pytest.approx(1.0, abs=1e-6)
    # overlapped: the 3 ms idle gap is the loss — 2 ms to plan, 1 ms to
    # dispatch; sync rides alongside; the rest is device compute
    assert w["frac"]["plan"] == pytest.approx(0.2, abs=0.01)
    assert w["frac"]["dispatch"] == pytest.approx(0.1, abs=0.01)
    assert w["frac"]["sync"] == pytest.approx(0.05, abs=0.01)
    assert w["frac"]["queue_wait"] == 0.0
    device = sum(w["frac"][k] for k in ("attention", "mlp", "lm_head",
                                        "sampling"))
    assert device == pytest.approx(0.65, abs=0.02)
    assert w["roofline_frac"] is not None and w["roofline_frac"] > 0
    assert w["achieved_tok_s"] == pytest.approx(6400.0, rel=0.01)


def test_ledger_serial_partition_charges_sync_as_device():
    led = _ledger(clock=_tick(0.010))
    for _ in range(32):
        led.note_step(
            "decode", 0.010, batch=8, tokens=8, context_tokens=8 * 64,
            plan_ms=2.0, dispatch_ms=1.0, sync_ms=5.0, idle_gap_ms=3.0,
            overlapped=False,
        )
    w = led.window_summary()
    assert sum(w["frac"].values()) == pytest.approx(1.0, abs=1e-6)
    # serial: the harvest block IS the device executing; idle_gap would
    # double count the plan/emit time and stays 0
    assert w["frac"]["idle_gap"] == 0.0
    assert w["frac"]["sync"] == 0.0
    device = sum(w["frac"][k] for k in ("attention", "mlp", "lm_head",
                                        "sampling"))
    assert device == pytest.approx(0.5, abs=0.02)  # 5 ms of 10
    assert w["frac"]["queue_wait"] == pytest.approx(0.2, abs=0.02)  # residual


def test_ledger_note_idle_breaks_the_timeline():
    clock = _tick(0.0)
    led = _ledger(clock=clock)
    led.note_step("decode", 0.010, batch=4, tokens=4, overlapped=True)
    led.note_idle()
    # a 100 s park with no work must NOT bill 100 s to the next step
    for _ in range(10000):
        clock()
    led.note_step("decode", 0.010, batch=4, tokens=4, overlapped=True)
    w = led.window_summary()
    assert w["span_s"] < 1.0


def test_ledger_anomaly_band_trips_on_roofline_drop():
    led = _ledger(clock=_tick(0.010), anomaly_check_every=8)
    kw = dict(batch=64, tokens=64, context_tokens=64 * 192, overlapped=True)

    def run(n, dt):
        led._clock = _tick(dt)
        hits = []
        for _ in range(n):
            r = led.note_step("decode", dt, **kw)
            if r:
                hits.append(r)
        return hits

    assert run(64, 0.012) == []  # healthy baseline seeds the EMA
    hits = run(64, 0.30)  # 25x slower: frac collapses under the band
    assert hits and hits[0].startswith("roofline_drop:")


def test_blackbox_bundle_contents_and_rate_limit(tmp_path):
    from dynamo_tpu.telemetry.attribution import BlackBox

    led = _ledger(clock=_tick(0.01))
    led.note_step("decode", 0.01, batch=4, tokens=4, overlapped=True)
    rec = FlightRecorder(capacity=8)
    rec.record("decode", 0.001, batch=4)
    now = [0.0]
    bb = BlackBox(
        recorder=rec, ledger=led, dump_dir=str(tmp_path),
        min_interval_s=60.0, clock=lambda: now[0], profile_ms=0,
    )
    d = bb.trigger("watchdog:decode")
    assert d is not None
    bb.flush()  # snapshot is sync; the file write is a background thread
    assert os.path.isdir(d)
    meta = json.load(open(os.path.join(d, "meta.json")))
    assert meta["reason"] == "watchdog:decode"
    attr = json.load(open(os.path.join(d, "attribution.json")))
    assert attr["window"]["steps"] == 1
    flight = [
        json.loads(x)
        for x in open(os.path.join(d, "flight.jsonl")).read().splitlines()
    ]
    assert flight[0]["flight_recorder_dump"] is True
    assert flight[1]["kind"] == "decode"
    assert os.path.exists(os.path.join(d, "state.json"))
    # second trigger inside the window: suppressed
    assert bb.trigger("watchdog:decode") is None
    assert bb.stats()["dumps"] == 1 and bb.stats()["suppressed"] == 1
    now[0] = 61.0
    assert bb.trigger("roofline_drop:x") is not None
    bb.flush()
    assert bb.stats()["dumps"] == 2


# ---------------------------------------------------------------------------
# perf attribution: e2e — ledger under the real pipelines, endpoint,
# metrics agreement, fault-stall black box
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("decode_steps", [1, 4])
async def test_e2e_attribution_sums_under_pipelines(decode_steps):
    """Acceptance bar: a steady decode window's component fractions sum
    to 1.0 ± 0.05 under both the overlapped single-step pipeline
    (decode_steps=1) and the fused window pipeline (decode_steps>1)."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(decode_steps=decode_steps))
    try:
        await asyncio.gather(*[
            _gen(engine, range(1, 24), max_tokens=24, request_id=f"a{i}")
            for i in range(4)
        ])
        snap = engine.attribution.snapshot()
        assert snap["configured"] is True
        w = snap["window"]
        assert w["steps"] >= 4
        assert sum(w["frac"].values()) == pytest.approx(1.0, abs=0.05)
        assert w["achieved_tok_s"] > 0
        # decode happened, so the ceiling math engaged
        assert w["roofline_frac"] is not None and w["roofline_frac"] > 0
        assert w["top_loss_bucket"] != ""
        assert sum(w["tokens_lost_per_s"].values()) >= 0
        # the load feed carries the signals (metrics-service rollup input)
        fpm = engine.stats()
        assert fpm.roofline_frac == pytest.approx(w["roofline_frac"])
        assert fpm.top_loss_bucket == w["top_loss_bucket"]
    finally:
        await engine.shutdown()


async def test_e2e_debug_attribution_endpoint_and_metrics_agree():
    """/debug/attribution schema + /metrics agreement: the gauge family
    the ledger publishes must match the snapshot the endpoint serves."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg())
    service = None
    try:
        await _gen(engine, range(1, 24), max_tokens=16, request_id="attr")
        # the final harvest's attribution record can trail the stream
        # close by an engine-thread tick (the pipelines emit before they
        # record); snapshotting mid-trail compares two different windows
        # — wait for the ledger to quiesce before fetching
        await engine.wait_for_state(lambda e: not e.scheduler.has_work)
        last = -1
        while engine.attribution.steps_noted != last:
            last = engine.attribution.steps_noted
            await asyncio.sleep(0.05)
        service, base = await _start_frontend()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/attribution") as r:
                assert r.status == 200
                state = await r.json()
            async with s.get(f"{base}/metrics") as r:
                metrics_text = await r.text()
        eng = state["engine"]
        attr, bb = eng["attribution"], eng["blackbox"]
        assert attr["configured"] is True
        w = attr["window"]
        assert set(w["frac"]) == {
            "queue_wait", "plan", "dispatch", "sync", "idle_gap",
            "attention", "mlp", "lm_head", "sampling",
        }
        assert sum(w["frac"].values()) == pytest.approx(1.0, abs=0.05)
        assert attr["recent"], "recent per-step rows missing"
        assert {"kind", "interval_ms", "buckets_ms"} <= set(attr["recent"][0])
        assert bb["dumps"] == 0 and "dump_dir" in bb
        # /metrics agreement: the endpoint's provider refreshes the
        # gauges, so the scrape and the snapshot describe one window
        fams = prom_parse(metrics_text)
        assert fams["dynamo_roofline_frac"].samples[
            ("dynamo_roofline_frac", ())
        ] == pytest.approx(w["roofline_frac"], rel=1e-6)
        frac_samples = fams["dynamo_step_time_frac"].samples
        for comp, frac in w["frac"].items():
            got = frac_samples[
                ("dynamo_step_time_frac", (("component", comp),))
            ]
            assert got == pytest.approx(frac, abs=1e-6), comp
        assert fams["dynamo_tokens_lost_per_s"].type == "gauge"
        # /debug/state carries the same stanza for `top`
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/state") as r:
                ds = await r.json()
        assert ds["engine"]["attribution"]["window"]["steps"] == w["steps"]
    finally:
        if service is not None:
            await service.stop()
        await engine.shutdown()


async def test_e2e_stall_fires_exactly_one_blackbox(tmp_path, monkeypatch):
    """An injected engine.step stall (DYN_FAULTS) trips the slow-step
    watchdog; the black box bundles recorder tail + attribution window
    exactly ONCE per rate-limit window despite repeated stalls."""
    from dynamo_tpu import faults
    from dynamo_tpu.engine.engine import JaxEngine

    monkeypatch.setenv("DYN_BLACKBOX_INTERVAL_S", "3600")
    injector = faults.activate(faults.parse_plan(
        "seed=7;engine.step:delay=0.06@p=1.0"
    ))
    engine = await JaxEngine.launch(_engine_cfg(
        slow_step_ms=30.0, flight_dump_dir=str(tmp_path),
    ))
    try:
        await _gen(engine, range(1, 16), max_tokens=12, request_id="stall")
        assert injector.stats()["fired_total"] > 3  # repeated stalls
        engine.blackbox.flush()
        bundles = [
            p for p in os.listdir(tmp_path)
            if p.startswith("dynamo_blackbox_")
        ]
        assert len(bundles) == 1, bundles
        d = os.path.join(str(tmp_path), bundles[0])
        meta = json.load(open(os.path.join(d, "meta.json")))
        assert meta["reason"].startswith("watchdog:")
        # recorder tail + attribution window both present (acceptance)
        flight = open(os.path.join(d, "flight.jsonl")).read().splitlines()
        assert len(flight) >= 2
        attr = json.load(open(os.path.join(d, "attribution.json")))
        assert attr["window"]["steps"] >= 1
        assert engine.blackbox.stats()["dumps"] == 1
        assert engine.blackbox.stats()["suppressed"] >= 0
    finally:
        faults.deactivate()
        await engine.shutdown()


# ---------------------------------------------------------------------------
# bench sentinel comparison logic (bench.py --sentinel)
# ---------------------------------------------------------------------------
def test_sentinel_flags_inflated_baseline_and_names_bucket():
    """The acceptance case: a baseline 20% above the measured headline
    exits nonzero (noise band 15%) and names the losing bucket."""
    import bench

    measured = {
        "tok_s": 1000.0,
        "roofline_frac": 0.30,
        "step_time_frac": {"plan": 0.30, "mlp": 0.50, "sync": 0.20},
    }
    base = {
        "tok_s": 1250.0,  # measured is 20% below
        "noise_frac": 0.15,
        "roofline_frac": 0.375,
        "step_time_frac": {"plan": 0.10, "mlp": 0.65, "sync": 0.25},
        "bucket_noise_abs": 0.05,
    }
    v = bench._sentinel_compare(measured, base)
    assert v["regressed"] is True
    assert v["losing_bucket"] == "plan"  # +0.20 of step time
    assert v["bucket_deltas"]["plan"] == pytest.approx(0.20)
    assert v["floor_tok_s"] == pytest.approx(1062.5)


def test_sentinel_passes_inside_noise_band():
    import bench

    measured = {"tok_s": 980.0, "roofline_frac": 0.3,
                "step_time_frac": {"plan": 0.1}}
    base = {"tok_s": 1000.0, "noise_frac": 0.15,
            "step_time_frac": {"plan": 0.12}, "bucket_noise_abs": 0.05}
    v = bench._sentinel_compare(measured, base)
    assert v["regressed"] is False
    assert v["losing_bucket"] == ""


def test_sentinel_uniform_slowdown_does_not_blame_a_shrinking_bucket():
    """A global slowdown moves every bucket frac slightly negative or
    not at all; the fallback must say 'uniform', not name the
    least-shrunk bucket as the culprit."""
    import bench

    measured = {"tok_s": 500.0,
                "step_time_frac": {"plan": 0.09, "mlp": 0.61}}
    base = {"tok_s": 1000.0, "noise_frac": 0.15,
            "step_time_frac": {"plan": 0.10, "mlp": 0.62},
            "bucket_noise_abs": 0.05}
    v = bench._sentinel_compare(measured, base)
    assert v["regressed"] is True
    assert v["losing_bucket"] == "uniform"


def test_sentinel_profile_keys_split_platform_and_tier():
    import bench

    wl = {"model_name": "tiny"}
    assert bench._sentinel_profile_key(True, wl, True) == "cpu-tiny-quick"
    assert bench._sentinel_profile_key(False, wl, False) == "tpu-tiny-full"
    # the DYN_BENCH_SPEC=0 escape hatch runs a different step program
    # (fused windows vs the spec pipeline) — its baseline must not
    # share a key with the spec headline's
    assert (
        bench._sentinel_profile_key(True, wl, True, spec=False)
        == "cpu-tiny-quick-nospec"
    )


def test_committed_baseline_has_the_ci_profile():
    """CI runs `--sentinel --quick` on CPU against the committed file —
    the profile it compares against must exist with explicit bands."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "BENCH_BASELINE.json")
    data = json.load(open(path))
    prof = data["profiles"]["cpu-tiny-quick"]
    assert prof["tok_s"] > 0
    assert 0 < prof["noise_frac"] < 1
    assert 0 < prof["bucket_noise_abs"] < 1
    assert set(prof["step_time_frac"]) <= {
        "queue_wait", "plan", "dispatch", "sync", "idle_gap",
        "attention", "mlp", "lm_head", "sampling",
    }


# ---------------------------------------------------------------------------
# top: ROOF%/LOSS columns, --watch-roofline, tok/s absence marker
# ---------------------------------------------------------------------------
async def test_top_roofline_column_and_watch_sort():
    from dynamo_tpu.cli.top import run_top

    def eng(roof, bucket, toks):
        return {
            "model": "tiny", "max_batch_size": 8,
            "tokens_generated_total": toks,
            "scheduler": {"running": 1, "queue_depth": 0, "preemptions": 0},
            "kv_pool": {"usage": 0.1},
            "slo": {"enabled": False},
            "hbm": {"bytes_in_use": 1024},
            "flight_recorder": {"slow_steps": 0},
            "attribution": {"window": {
                "roofline_frac": roof, "top_loss_bucket": bucket,
            }},
        }

    tdebug.register_debug_provider("engine", lambda: eng(0.37, "idle_gap", 5))
    service, base = await _start_frontend()
    tdebug.register_debug_provider(
        "engine2", lambda: {"noise": True}  # second provider: ignored
    )
    try:
        buf = io.StringIO()
        rc = await run_top([base], interval=0.01, iterations=1,
                           clear=False, out=buf, watch_roofline=True)
        assert rc == 0
        text = buf.getvalue()
        assert "ROOF%" in text and "LOSS" in text
        assert "37.0%" in text
        assert "idle_gap" in text
        # first poll: no token delta -> the absence marker, never 0.0
        assert "       -" in text
    finally:
        tdebug.unregister_debug_provider("engine")
        tdebug.unregister_debug_provider("engine2")
        await service.stop()


async def test_top_counter_reset_renders_absence_not_zero():
    """A worker restart rewinds tokens_generated_total; the rate must
    render `-` (no delta), not clamp to a fabricated 0.0."""
    from dynamo_tpu.cli.top import _engine_row

    prev = {"engine": {"tokens_generated_total": 10_000}}
    cur = {"engine": {"tokens_generated_total": 50}}  # restarted worker
    row = _engine_row("u", cur, prev, now=10.0, prev_ts=8.0)
    assert row["tok_s"] is None
    ok = _engine_row(
        "u", {"engine": {"tokens_generated_total": 150}},
        {"engine": {"tokens_generated_total": 50}}, now=12.0, prev_ts=10.0,
    )
    assert ok["tok_s"] == pytest.approx(50.0)


def test_top_watch_roofline_parser_wiring():
    from dynamo_tpu.cli.main import build_parser

    args = build_parser().parse_args(["top", "--watch-roofline", "--once"])
    assert args.watch_roofline is True
    assert build_parser().parse_args(["top"]).watch_roofline is False


# ---------------------------------------------------------------------------
# metrics service rollup
# ---------------------------------------------------------------------------
def test_metrics_service_rolls_up_slo_signals():
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.metrics.service import MetricsService

    svc = MetricsService(component=None, host="127.0.0.1", port=0)  # type: ignore[arg-type]
    svc.aggregator.update(ForwardPassMetrics(
        worker_id=1, slo_enabled=True, slo_attainment=0.5,
        goodput_tokens_total=100, roofline_frac=0.30,
        top_loss_bucket="idle_gap",
    ))
    svc.aggregator.update(ForwardPassMetrics(
        worker_id=2, slo_enabled=True, slo_attainment=1.0,
        goodput_tokens_total=300, roofline_frac=0.50,
    ))
    # a target-less worker reports the default 1.0 — it must NOT
    # dilute the fleet attainment mean; its default roofline_frac of
    # -1.0 (no decode window yet) is likewise excluded from the mean
    svc.aggregator.update(ForwardPassMetrics(worker_id=3))
    fams = prom_parse(svc.render())
    assert fams["llm_slo_attainment"].samples[
        ("llm_slo_attainment", ())
    ] == 0.75
    assert fams["llm_goodput_tokens"].samples[
        ("llm_goodput_tokens", ())
    ] == 400
    assert fams["llm_roofline_frac"].samples[
        ("llm_roofline_frac", ())
    ] == pytest.approx(0.40)
