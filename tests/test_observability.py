"""Live introspection + performance attribution (ISSUE 4): flight
recorder ring/dump semantics, SLO attainment math, HBM accounting
fallback, the /debug/state + /debug/profile endpoints, the
`dynamo-tpu top` fleet view, and the e2e acceptance path — a slow
request produces a JSONL flight dump whose offending step carries
per-phase latency, while /debug/state and /metrics agree on KV-pool
occupancy for the same moment."""

import asyncio
import io
import json
import os
import time

import aiohttp
import pytest

from dynamo_tpu.telemetry import debug as tdebug
from dynamo_tpu.telemetry.hbm import HbmAccountant, tree_bytes
from dynamo_tpu.telemetry.recorder import FlightRecorder
from dynamo_tpu.telemetry.slo import SloConfig, SloTracker

from tests.prom_parser import parse as prom_parse

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
def test_flight_recorder_ring_is_bounded():
    rec = FlightRecorder(capacity=8)
    for i in range(50):
        rec.record("decode", 0.001, batch=i)
    snap = rec.snapshot(100)
    assert len(snap) == 8  # deque(maxlen=8): old entries fell off
    assert [r["batch"] for r in snap] == list(range(42, 50))
    assert rec.steps_recorded == 50


def test_flight_recorder_slow_step_dumps(tmp_path):
    rec = FlightRecorder(
        capacity=16, slow_step_s=0.010, dump_dir=str(tmp_path),
        min_dump_interval_s=0.0,
    )
    for _ in range(5):
        assert rec.record("decode", 0.001, batch=4) is None  # under threshold
    path = rec.record(
        "prefill", 0.050, batch=2, dispatch_ms=48.0, sync_ms=1.5,
        plan_ms=0.3,
    )
    assert path is not None and os.path.exists(path)
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    header, records = lines[0], lines[1:]
    assert header["flight_recorder_dump"] is True
    assert header["reason"] == "slow_step:prefill"
    assert len(records) == 6
    slow = [r for r in records if r.get("slow")]
    assert len(slow) == 1
    # the offending step carries its per-phase latency breakdown
    assert slow[0]["kind"] == "prefill"
    assert slow[0]["duration_ms"] == pytest.approx(50.0)
    assert slow[0]["dispatch_ms"] == 48.0
    assert slow[0]["sync_ms"] == 1.5
    assert slow[0]["plan_ms"] == 0.3
    assert slow[0]["slow_threshold_ms"] == pytest.approx(10.0)


def test_flight_recorder_dumps_are_rate_limited(tmp_path):
    now = [0.0]
    rec = FlightRecorder(
        capacity=4, slow_step_s=0.001, dump_dir=str(tmp_path),
        min_dump_interval_s=30.0, clock=lambda: now[0],
    )
    assert rec.record("decode", 0.5) is not None
    assert rec.record("decode", 0.5) is None  # suppressed: too soon
    assert rec.slow_steps == 2  # still counted as slow
    now[0] = 31.0
    assert rec.record("decode", 0.5) is not None  # window elapsed
    assert rec.dumps_written == 2


def test_flight_recorder_failed_dump_does_not_arm_rate_limit(tmp_path):
    rec = FlightRecorder(
        capacity=4, slow_step_s=0.001,
        dump_dir=os.path.join(str(tmp_path), "missing", "dir"),
        min_dump_interval_s=3600.0,
    )
    assert rec.record("decode", 0.5) is None  # write failed (no dir)
    rec.dump_dir = str(tmp_path)
    # a failed dump persisted nothing, so the next trigger must not be
    # suppressed by the rate limiter
    assert rec.record("decode", 0.5) is not None


def test_flight_recorder_caps_on_disk_dump_files(tmp_path):
    rec = FlightRecorder(
        capacity=4, slow_step_s=0.001, dump_dir=str(tmp_path),
        min_dump_interval_s=0.0, max_dump_files=3,
    )
    paths = [rec.record("decode", 0.5) for _ in range(5)]
    assert all(paths)
    on_disk = sorted(
        p for p in os.listdir(tmp_path) if p.startswith("dynamo_flight_")
    )
    # dumps 1 and 2 were unlinked when 4 and 5 landed: disk is bounded
    assert len(on_disk) == 3
    assert on_disk == [os.path.basename(p) for p in paths[-3:]]


def test_flight_recorder_slow_request_dump(tmp_path):
    rec = FlightRecorder(
        capacity=8, dump_dir=str(tmp_path), min_dump_interval_s=0.0,
    )
    rec.record("decode", 0.001)
    path = rec.note_slow_request("req-9", ttft_ms=812.0, tokens=30)
    assert path is not None
    lines = [json.loads(x) for x in open(path).read().splitlines()]
    assert lines[0]["reason"] == "slow_request:req-9"
    marker = [r for r in lines[1:] if r.get("kind") == "slow_request"]
    assert marker and marker[0]["request_id"] == "req-9"
    assert marker[0]["ttft_ms"] == 812.0


# ---------------------------------------------------------------------------
# SLO attainment / goodput math
# ---------------------------------------------------------------------------
def test_slo_attainment_math():
    t = SloTracker(SloConfig(ttft_ms=100.0, itl_ms=10.0), window=16)
    assert t.attainment == 1.0  # nothing observed yet
    assert t.observe(0.050, 0.005, completion_tokens=10) is True
    assert t.observe(0.200, 0.005, completion_tokens=10) is False  # ttft miss
    assert t.observe(0.050, 0.020, completion_tokens=10) is False  # itl miss
    assert t.observe(0.050, None, completion_tokens=5) is True  # no itl: n/a
    assert t.attainment == pytest.approx(2 / 4)
    assert t.goodput_tokens == 15  # only SLO-met requests count
    s = t.stats()
    assert s["requests_seen"] == 4 and s["requests_met"] == 2
    assert s["targets"] == {"ttft_ms": 100.0, "itl_ms": 10.0}


def test_slo_rolling_window_forgets_old_outcomes():
    t = SloTracker(SloConfig(ttft_ms=100.0), window=4)
    for _ in range(4):
        t.observe(1.0, None)  # all miss
    assert t.attainment == 0.0
    for _ in range(4):
        t.observe(0.01, None)  # all meet: misses roll out of the window
    assert t.attainment == 1.0


def test_aggregate_slo_shared_rollup():
    from dynamo_tpu.telemetry.slo import aggregate_slo

    class W:
        def __init__(self, enabled, attain, goodput):
            self.slo_enabled = enabled
            self.slo_attainment = attain
            self.goodput_tokens_total = goodput

    attainment, goodput = aggregate_slo([
        W(True, 0.5, 100), W(True, 1.0, 300),
        W(False, 1.0, 0),  # target-less: excluded from the mean
    ])
    assert attainment == 0.75 and goodput == 400
    assert aggregate_slo([]) == (1.0, 0.0)
    assert aggregate_slo([W(False, 1.0, 50)]) == (1.0, 50.0)


async def test_errored_requests_do_not_score_slo(tmp_path):
    """ERROR finishes must not count as goodput or attainment: a fleet
    in an error loop reporting 'healthy' would invert the Planner
    signal."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(
        slo_ttft_ms=60_000.0, flight_dump_dir=str(tmp_path),
    ))
    try:
        await engine.wait_for_state(lambda e: e.scheduler is not None)

        def always_boom(*a, **kw):
            raise RuntimeError("persistent failure")

        engine._run_device_step = always_boom
        engine._dispatch_mixed = always_boom
        engine._dispatch_multi_step = always_boom
        out = await _gen(engine, range(1, 12), request_id="err")
        assert out == []
        assert engine.slo.requests_seen == 0
        assert engine.slo.goodput_tokens == 0
        assert engine.slo.attainment == 1.0
    finally:
        await engine.shutdown()


def test_slo_disabled_records_but_does_not_score():
    t = SloTracker(SloConfig())
    assert not t.config.enabled
    assert t.observe(99.0, 99.0, completion_tokens=100) is True
    assert t.attainment == 1.0
    assert t.goodput_tokens == 0
    assert t.requests_seen == 0


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------
def test_hbm_accountant_portable_fallback():
    acct = HbmAccountant(device=None)
    acct.set_static(weight_bytes=1000, kv_pool_bytes=500)
    snap = acct.refresh()
    assert snap["source"] == "accounted"
    assert snap["weight_bytes"] == 1000
    assert snap["kv_pool_bytes"] == 500
    assert snap["bytes_in_use"] == 1500
    assert snap["peak_bytes_in_use"] == 1500
    acct.set_static(weight_bytes=100, kv_pool_bytes=50)
    snap2 = acct.refresh()
    assert snap2["bytes_in_use"] == 150
    assert snap2["peak_bytes_in_use"] == 1500  # watermark held


def test_tree_bytes_counts_nested_arrays():
    import numpy as np

    tree = {"a": np.zeros((4, 4), np.float32),
            "kv": (np.zeros(8, np.int8), np.zeros(2, np.float32))}
    assert tree_bytes(tree) == 64 + 8 + 8


# ---------------------------------------------------------------------------
# debug provider registry
# ---------------------------------------------------------------------------
def test_debug_provider_registry_isolation():
    def good():
        return {"x": 1}

    def bad():
        raise RuntimeError("torn read")

    tdebug.register_debug_provider("t_good", good)
    tdebug.register_debug_provider("t_bad", bad)
    try:
        state = tdebug.collect_debug_state()
        assert state["t_good"] == {"x": 1}
        # a raising provider degrades to an error stanza, not a crash
        assert "RuntimeError" in state["t_bad"]["error"]
        assert "ts" in state and "pid" in state
    finally:
        tdebug.unregister_debug_provider("t_good")
        tdebug.unregister_debug_provider("t_bad")
    assert "t_good" not in tdebug.debug_provider_names()


def test_debug_provider_unregister_checks_identity():
    tdebug.register_debug_provider("t_ident", lambda: {"v": 2})
    try:
        # a DIFFERENT provider under the same name must not be yanked
        tdebug.unregister_debug_provider("t_ident", lambda: {"v": 3})
        assert "t_ident" in tdebug.debug_provider_names()
    finally:
        tdebug.unregister_debug_provider("t_ident")


# ---------------------------------------------------------------------------
# /debug endpoints on the HTTP frontend
# ---------------------------------------------------------------------------
async def _start_frontend():
    from dynamo_tpu.http.service import HttpService, ModelManager

    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}"


async def test_debug_state_endpoint_schema():
    tdebug.register_debug_provider("t_worker", lambda: {"busy": True})
    service, base = await _start_frontend()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/state") as r:
                assert r.status == 200
                state = await r.json()
        assert state["t_worker"] == {"busy": True}
        assert state["frontend"]["models"] == []
        assert state["frontend"]["port"] == service.port
    finally:
        tdebug.unregister_debug_provider("t_worker")
        await service.stop()


async def test_debug_profile_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("DYN_PROFILE_DIR", str(tmp_path))
    service, base = await _start_frontend()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/profile?ms=50") as r:
                assert r.status == 200, await r.text()
                body = await r.json()
            assert body["duration_ms"] == 50
            assert os.path.isdir(body["trace_dir"])
            assert body["trace_dir"].startswith(str(tmp_path))
            async with s.get(f"{base}/debug/profile?ms=nope") as r:
                assert r.status == 400
    finally:
        await service.stop()


# ---------------------------------------------------------------------------
# e2e acceptance: slow request -> flight dump; /debug/state vs /metrics
# ---------------------------------------------------------------------------
def _engine_cfg(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    defaults = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=128, block_size=8, max_batch_size=8,
        prefill_chunk_size=32, max_model_len=256,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _gen(engine, prompt, max_tokens=8, request_id="r"):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        request_id=request_id, token_ids=list(prompt),
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens),
    )
    out = []
    async for item in engine.as_async_engine().generate(req, Context()):
        out.extend(item.token_ids)
    return out


async def test_e2e_slow_step_dump_and_consistent_kv_occupancy(tmp_path):
    """The acceptance bar: an injected device-step delay trips the
    slow-step watchdog, the dump contains the offending step WITH its
    per-phase latency, and /debug/state + /metrics agree on KV-pool
    occupancy for the same moment."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(
        slow_step_ms=40.0,
        flight_dump_dir=str(tmp_path),
        slo_ttft_ms=10_000.0,  # generous: CPU test backend
    ))
    service = None
    try:
        # inject a delay into every synced device step
        orig = engine._run_device_step

        def slow_step(arrays, sampling, **kw):
            time.sleep(0.08)
            return orig(arrays, sampling, **kw)

        engine._run_device_step = slow_step
        toks = await _gen(engine, range(1, 20), request_id="slowreq")
        assert len(toks) == 8
        engine._run_device_step = orig

        # -- flight dump: offending step + per-phase latency ------------
        dumps = sorted(
            p for p in os.listdir(tmp_path) if p.startswith("dynamo_flight_")
        )
        assert dumps, "slow steps produced no flight-recorder dump"
        lines = [
            json.loads(x)
            for x in open(os.path.join(tmp_path, dumps[0])).read().splitlines()
        ]
        assert lines[0]["reason"].startswith("slow_step:")
        slow_recs = [r for r in lines[1:] if r.get("slow")]
        assert slow_recs, "dump lacks the offending step"
        off = slow_recs[0]
        assert off["duration_ms"] > 40.0
        assert "dispatch_ms" in off  # per-phase latency present
        assert "plan_ms" in off
        assert off["queue_depth"] >= 0 and "batch" in off

        # -- /debug/state vs /metrics occupancy -------------------------
        service, base = await _start_frontend()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/state") as r:
                assert r.status == 200
                state = await r.json()
            async with s.get(f"{base}/metrics") as r:
                metrics_text = await r.text()
        eng = state["engine"]
        pool = eng["kv_pool"]
        fams = prom_parse(metrics_text)
        active = fams["dynamo_kv_pool_blocks_active"].samples[
            ("dynamo_kv_pool_blocks_active", ())
        ]
        total = fams["dynamo_kv_pool_blocks_total"].samples[
            ("dynamo_kv_pool_blocks_total", ())
        ]
        assert pool["active_blocks"] == active
        assert pool["total_blocks"] == total == 127
        assert pool["active_blocks"] + pool["free_blocks"] == total
        # the engine snapshot carries the rest of the introspection
        # surface the CLI renders
        assert eng["scheduler"]["running"] == 0
        assert eng["hbm"]["kv_pool_bytes"] > 0
        assert eng["slo"]["enabled"] is True
        assert eng["slo"]["requests_seen"] >= 1
        assert eng["recent_steps"], "flight recorder tail missing"
        assert eng["load"]["goodput_tokens_total"] >= 8
        # SLO histograms made it into the exposition machinery
        assert fams["dynamo_request_ttft_seconds"].type == "histogram"
        assert fams["dynamo_slo_attainment"].samples[
            ("dynamo_slo_attainment", ())
        ] == 1.0
    finally:
        if service is not None:
            await service.stop()
        await engine.shutdown()


async def test_slo_miss_scores_and_dumps(tmp_path):
    """An impossible ITL target: the request misses, attainment drops,
    and the request watchdog dumps the ring."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_engine_cfg(
        slo_ttft_ms=100_000.0, slo_itl_ms=0.0001,
        flight_dump_dir=str(tmp_path),
    ))
    try:
        await _gen(engine, range(1, 16), request_id="misser")
        assert engine.slo.attainment < 1.0
        assert engine.slo.goodput_tokens == 0
        dumps = [
            p for p in os.listdir(tmp_path) if p.startswith("dynamo_flight_")
        ]
        assert dumps, "SLO miss did not trip the request watchdog"
    finally:
        await engine.shutdown()


# ---------------------------------------------------------------------------
# dynamo-tpu top
# ---------------------------------------------------------------------------
async def test_top_renders_fleet_frame():
    from dynamo_tpu.cli.top import run_top

    tokens = [1000]

    def fake_engine():
        tokens[0] += 500
        return {
            "model": "tiny-model",
            "max_batch_size": 8,
            "tokens_generated_total": tokens[0],
            "scheduler": {"running": 3, "queue_depth": 2, "preemptions": 1},
            "kv_pool": {"usage": 0.25, "active_blocks": 32,
                        "total_blocks": 128},
            "slo": {"enabled": True, "attainment": 0.875},
            "hbm": {"bytes_in_use": 2 * 1024 * 1024},
            "flight_recorder": {"slow_steps": 4},
            "load": {"goodput_tokens_total": 0},  # no SLO targets: tok/s
            # must NOT come from goodput
        }

    tdebug.register_debug_provider("engine", fake_engine)
    service, base = await _start_frontend()
    try:
        buf = io.StringIO()
        rc = await run_top([base], interval=0.01, iterations=2,
                           clear=False, out=buf)
        assert rc == 0
        text = buf.getvalue()
        assert "WORKER" in text and "tiny-model" in text
        assert "25.0%" in text  # kv usage
        assert "87.5%" in text  # slo attainment
        assert "2.0MB" in text  # hbm
        # second frame derives a NONZERO rate from generated-token
        # deltas even though goodput is 0 (no SLO targets configured)
        frames = text.split("dynamo-tpu top")
        assert "       -" in frames[1]  # first frame: no delta yet
        import re

        rates = re.findall(r" (\d+\.\d)\b", frames[2])
        assert any(float(x) > 0 for x in rates), frames[2]
    finally:
        tdebug.unregister_debug_provider("engine")
        await service.stop()


async def test_top_raw_mode_and_dead_worker():
    from dynamo_tpu.cli.top import run_top

    buf = io.StringIO()
    # unroutable port: every worker erroring is exit code 1
    rc = await run_top(["http://127.0.0.1:1"], interval=0.01,
                       iterations=1, raw=True, out=buf)
    assert rc == 1
    row = json.loads(buf.getvalue())
    assert "error" in row["http://127.0.0.1:1"]


def test_top_cli_parser_wiring():
    from dynamo_tpu.cli.main import build_parser

    args = build_parser().parse_args(
        ["top", "http://h:1", "--once", "--raw", "--interval", "0.5"]
    )
    assert args.command == "top"
    assert args.urls == ["http://h:1"]
    assert args.once and args.raw and args.interval == 0.5
    run_args = build_parser().parse_args(
        ["run", "--slo-ttft-ms", "500", "--slo-itl-ms", "40",
         "--slow-step-ms", "250", "--flight-recorder-steps", "128"]
    )
    assert run_args.slo_ttft_ms == 500.0
    assert run_args.slo_itl_ms == 40.0
    assert run_args.slow_step_ms == 250.0
    assert run_args.flight_recorder_steps == 128
    from dynamo_tpu.engine.config import load_engine_config

    cfg = load_engine_config(run_args)
    assert cfg.slo_ttft_ms == 500.0 and cfg.slow_step_ms == 250.0
    assert cfg.flight_recorder_steps == 128


# ---------------------------------------------------------------------------
# metrics service rollup
# ---------------------------------------------------------------------------
def test_metrics_service_rolls_up_slo_signals():
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.metrics.service import MetricsService

    svc = MetricsService(component=None, host="127.0.0.1", port=0)  # type: ignore[arg-type]
    svc.aggregator.update(ForwardPassMetrics(
        worker_id=1, slo_enabled=True, slo_attainment=0.5,
        goodput_tokens_total=100,
    ))
    svc.aggregator.update(ForwardPassMetrics(
        worker_id=2, slo_enabled=True, slo_attainment=1.0,
        goodput_tokens_total=300,
    ))
    # a target-less worker reports the default 1.0 — it must NOT
    # dilute the fleet attainment mean
    svc.aggregator.update(ForwardPassMetrics(worker_id=3))
    fams = prom_parse(svc.render())
    assert fams["llm_slo_attainment"].samples[
        ("llm_slo_attainment", ())
    ] == 0.75
    assert fams["llm_goodput_tokens"].samples[
        ("llm_goodput_tokens", ())
    ] == 400
