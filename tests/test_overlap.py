"""Overlapped decode pipeline (docs/performance.md): bit-identity vs
the serial loop, late-stop rollback, preemption/block-pressure safety,
the cohort-graduation window entry, and the OverlapTracker /
flight-recorder idle-gap plumbing. CPU-runnable tier-1, like
tests/test_spec.py."""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.telemetry.overlap import OverlapTracker

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


# ---------------------------------------------------------------------------
# OverlapTracker units (fake clock)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_tracker_counts_idle_gap_only_when_queue_empty():
    clk = _Clock()
    tr = OverlapTracker(clock=clk)
    assert tr.note_dispatch() == 0.0  # no completion anchor yet
    clk.t = 1.0
    tr.note_complete()
    clk.t = 1.5
    # queue empty + anchored: the 0.5 s host-side span is device idle
    assert tr.note_dispatch() == pytest.approx(0.5)
    # second dispatch while one is in flight: device has queued work
    clk.t = 1.6
    assert tr.note_dispatch() == 0.0
    clk.t = 2.0
    tr.note_complete()  # oldest harvested; one still in flight
    clk.t = 3.0
    assert tr.note_dispatch() == 0.0  # nonempty queue -> no idle
    s = tr.stats()
    assert s["steps_dispatched"] == 4
    assert s["idle_events"] == 1
    assert s["idle_gap_s_total"] == pytest.approx(0.5)
    assert s["max_idle_gap_ms"] == pytest.approx(500.0)


def test_tracker_all_prior_retirement_and_idle_reset():
    clk = _Clock()
    tr = OverlapTracker(clock=clk)
    tr.note_dispatch()
    tr.note_dispatch()  # e.g. sync=False prefill + synced step
    clk.t = 1.0
    tr.note_complete(all_prior=True)  # the newest sync retires both
    assert tr.inflight == 0
    # note_idle drops the anchor: a no-work wait is not device idleness
    tr.note_idle()
    clk.t = 10.0
    assert tr.note_dispatch() == 0.0
    # reset forgets a poisoned queue (aborted dispatch)
    tr.note_dispatch()
    tr.reset()
    assert tr.inflight == 0


def test_recorder_idle_gap_watchdog_dumps(tmp_path):
    from dynamo_tpu.telemetry.recorder import FlightRecorder

    clk = _Clock()
    rec = FlightRecorder(
        capacity=8, slow_step_s=10.0, dump_dir=str(tmp_path),
        idle_gap_slow_s=0.05, clock=clk,
    )
    # fast step, small gap: no dump
    assert rec.record("decode", 0.001, idle_gap_ms=1.0) is None
    clk.t = 100.0  # past the dump rate limit
    path = rec.record("decode", 0.001, idle_gap_ms=80.0)
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        lines = f.read().splitlines()
    assert '"reason": "idle_gap:decode"' in lines[0]
    assert any('"slow_idle_gap": true' in ln for ln in lines[1:])


# ---------------------------------------------------------------------------
# Engine: overlap vs serial bit-identity
# ---------------------------------------------------------------------------


def _engine_config(**kw):
    from dynamo_tpu.engine.config import EngineConfig

    base = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=64, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
    )
    base.update(kw)
    return EngineConfig(**base)


async def _generate(engine, prompt_ids, max_tokens=8, request_id="r",
                    temperature=None, seed=None, context=None):
    sampling = (
        SamplingOptions(use_greedy=True)
        if temperature is None
        else SamplingOptions(temperature=temperature, seed=seed)
    )
    req = PreprocessedRequest(
        request_id=request_id,
        token_ids=list(prompt_ids),
        sampling=sampling,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    out = []
    final = None
    async for item in engine.as_async_engine().generate(
        req, context or Context()
    ):
        out.extend(item.token_ids)
        if item.is_final:
            final = item
    return out, final


PROMPTS = [list(range(1, 12)), list(range(5, 21)), [7, 7, 3, 9, 1, 2]]


async def _decode_all(engine, max_tokens=9, temperature=None, seed=7):
    outs = await asyncio.gather(*[
        _generate(engine, p, max_tokens=max_tokens, request_id=f"r{i}",
                  temperature=temperature, seed=seed)
        for i, p in enumerate(PROMPTS)
    ])
    return [o[0] for o in outs]


async def test_overlap_greedy_bit_identical_vs_serial():
    """THE acceptance criterion: overlap on vs --no-overlap produce the
    same greedy tokens, token for token, at decode_steps=1 — and the
    overlap engine actually pipelined (dispatched with a step still in
    flight at least once). The sampled path must match too: the seed
    stream is identical, only offset by the in-flight lag."""
    from dynamo_tpu.engine.engine import JaxEngine

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        over = await _decode_all(eng)
        over_sampled = await _decode_all(eng, temperature=0.8)
        assert eng.overlap.steps_dispatched > 0
        dbg = eng.debug_state()["overlap"]
        assert dbg["enabled"] is True
    finally:
        await eng.shutdown()

    eng = await JaxEngine.launch(_engine_config(overlap=False))
    try:
        serial = await _decode_all(eng)
        serial_sampled = await _decode_all(eng, temperature=0.8)
        assert eng.debug_state()["overlap"]["enabled"] is False
    finally:
        await eng.shutdown()
    assert over == serial
    assert over_sampled == serial_sampled
    assert all(len(o) == 9 for o in over)


async def test_overlap_window_graduation_bit_identical():
    """decode_steps > 1: the cohort-graduation entry (prefill dispatch
    chaining first tokens on device into the first window) must not
    change greedy output vs the serial prefill -> window boundary."""
    from dynamo_tpu.engine.engine import JaxEngine

    eng = await JaxEngine.launch(_engine_config(decode_steps=4, overlap=True))
    try:
        over = await _decode_all(eng, max_tokens=11)
    finally:
        await eng.shutdown()
    eng = await JaxEngine.launch(_engine_config(decode_steps=4, overlap=False))
    try:
        serial = await _decode_all(eng, max_tokens=11)
    finally:
        await eng.shutdown()
    assert over == serial
    assert all(len(o) == 11 for o in over)


async def test_overlap_late_stop_discards_inflight_tokens():
    """Late-detected stop: a cancellation that lands while a step is in
    flight must terminate the stream with nothing extra emitted after
    the cancel is observed, free every block, and leave the prefix
    cache clean — a fresh continuation through the same engine matches
    a fresh engine's (post-stop tokens were never content-addressed)."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.runtime.engine import Context

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        free0 = eng.allocator.num_free
        ctx = Context()
        req = PreprocessedRequest(
            request_id="late-stop",
            token_ids=PROMPTS[0],
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=64, ignore_eos=True),
        )
        stream = eng.as_async_engine().generate(req, ctx)
        got = []
        async for item in stream:
            got.extend(item.token_ids)
            if len(got) >= 2:
                # the backend's stop-string detection cancels exactly
                # like this: via the context, one step late
                ctx.stop_generating()
                break
        # the engine reaps the cancelled sequence and frees its blocks
        await eng.wait_for_state(
            lambda e: not e.scheduler.running and not e.scheduler.waiting
            and not e.scheduler.prefilling
        )
        await eng.wait_for_state(
            lambda e: e.allocator.num_free == free0
        )
        # prefix-cache integrity: continuing prompt+got through the warm
        # cache matches a fresh engine (nothing past the stop committed)
        cont_warm, _ = await _generate(
            eng, PROMPTS[0] + got, max_tokens=4, request_id="cont"
        )
    finally:
        await eng.shutdown()
    fresh = await JaxEngine.launch(_engine_config(overlap=False))
    try:
        cont_fresh, _ = await _generate(
            fresh, PROMPTS[0] + got, max_tokens=4, request_id="cont2"
        )
    finally:
        await fresh.shutdown()
    assert cont_warm == cont_fresh


async def test_overlap_under_block_pressure_matches_roomy_engine():
    """Block exhaustion mid-pipeline: plan_pipelined_decode never
    preempts with a step in flight — it drains back to the serial
    planner, which preempts safely. Output under pressure (preemption +
    recompute) must equal a roomy engine's greedy output."""
    from dynamo_tpu.engine.engine import JaxEngine

    prompts = [list(range(1, 14)), list(range(3, 17)), list(range(2, 13))]

    async def run(num_blocks):
        eng = await JaxEngine.launch(
            _engine_config(overlap=True, num_blocks=num_blocks)
        )
        try:
            outs = await asyncio.gather(*[
                _generate(eng, p, max_tokens=16, request_id=f"p{i}")
                for i, p in enumerate(prompts)
            ])
            return [o[0] for o in outs], eng.scheduler.preemptions
        finally:
            await eng.shutdown()

    # 13 usable blocks of 8 tokens: the three sequences need ~12 at
    # their ends, so growth collides mid-decode and someone recomputes
    tight, _ = await run(14)
    roomy, roomy_preempt = await run(64)
    assert roomy_preempt == 0
    assert tight == roomy
    assert all(len(t) == 16 for t in tight)


async def test_overlap_records_phase_stamps():
    """The flight recorder's decode records carry the overlap phase
    stamps (overlap_ms / idle_gap_ms / sync_ms) so the win is
    measurable, not asserted — and /debug/state exposes the tracker."""
    from dynamo_tpu.engine.engine import JaxEngine

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        await _generate(eng, PROMPTS[0], max_tokens=6)
        recs = [r for r in eng.recorder.snapshot(64) if r["kind"] == "decode"]
        assert recs, "no decode records"
        piped = [r for r in recs if "overlap_ms" in r]
        assert piped, "no pipelined decode records"
        assert all("sync_ms" in r for r in piped)
        assert any("idle_gap_ms" in r for r in recs)
        dbg = eng.debug_state()["overlap"]
        assert dbg["steps_dispatched"] > 0
    finally:
        await eng.shutdown()
