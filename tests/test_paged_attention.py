"""Pallas paged-attention decode kernel vs the XLA reference path.

Runs the kernel in interpreter mode on the CPU backend (the fake-TPU rung
of the test ladder); the same code compiles natively on TPU.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.models.llama import paged_attention_reference
from dynamo_tpu.ops.paged_attention import paged_attention_decode


def _setup(B, H, Hk, Dh, num_blocks, bs, ctx_lens, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, H, Dh)).astype(np.float32)
    k = rng.standard_normal((num_blocks * bs, Hk, Dh)).astype(np.float32)
    v = rng.standard_normal((num_blocks * bs, Hk, Dh)).astype(np.float32)
    W = max((c + bs - 1) // bs for c in ctx_lens if c) if any(ctx_lens) else 1
    tables = np.zeros((B, W), np.int32)
    # assign distinct (non-zero) pages per sequence, scattered order
    next_page = 1
    for b, c in enumerate(ctx_lens):
        n = (c + bs - 1) // bs
        ids = np.arange(next_page, next_page + n, dtype=np.int32)
        rng.shuffle(ids)
        tables[b, :n] = ids
        next_page += n
    ctx = np.asarray(ctx_lens, np.int32)
    return (
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(tables), jnp.asarray(ctx),
    )


@pytest.mark.parametrize(
    "B,H,Hk,ctx_lens",
    [
        (2, 4, 2, [7, 29]),  # GQA, ragged contexts
        (3, 8, 1, [1, 33, 5]),  # MQA, ctx=1 edge
        (2, 4, 2, [40, 0]),  # padded row (ctx=0)
    ],
)
@pytest.mark.parametrize("window", [None, 24])
def test_decode_kernel_stacked_matches_per_layer(B, H, Hk, ctx_lens, window):
    """The stacked-cache kernel (layer via scalar prefetch — the engine's
    decode hot path, avoiding the per-layer slice copy) must match the
    per-layer kernel on every layer."""
    from dynamo_tpu.ops.paged_attention import paged_attention_decode_stacked

    Dh, bs, num_blocks, L = 128, 16, 16, 3
    rng = np.random.default_rng(7)
    q, k0, v0, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, ctx_lens)
    k_stack = jnp.asarray(
        rng.standard_normal((L, num_blocks * bs, Hk, Dh)).astype(np.float32)
    )
    v_stack = jnp.asarray(
        rng.standard_normal((L, num_blocks * bs, Hk, Dh)).astype(np.float32)
    )
    for layer in range(L):
        out = paged_attention_decode_stacked(
            q, k_stack, v_stack, jnp.int32(layer), tables, ctx, bs,
            sliding_window=window, interpret=True,
        )
        ref = paged_attention_decode(
            q, k_stack[layer], v_stack[layer], tables, ctx, bs,
            sliding_window=window, interpret=True,
        )
        valid = np.asarray(ctx) > 0
        np.testing.assert_allclose(
            np.asarray(out)[valid], np.asarray(ref)[valid],
            rtol=2e-2, atol=2e-2,
        )


@pytest.mark.parametrize(
    "B,H,Hk,T,starts,ctx_lens,window",
    [
        # full prefill from position 0, ragged lens, GQA
        (2, 4, 2, 32, [0, 0], [30, 17], None),
        # chunked: rows resume mid-prompt (prefix already in cache)
        (2, 4, 2, 16, [20, 5], [36, 21], None),
        # MQA + block-aligned + a padded row (start 0 / ctx 0)
        (3, 8, 1, 16, [0, 16, 0], [16, 32, 0], None),
        # sliding window across pages
        (2, 4, 2, 32, [0, 24], [32, 56], 20),
        # tile boundary: T = 2 tiles when tq divides (tiny tq via T=256
        # would be slow interpreted; T=32 runs one tile — covered above)
    ],
)
def test_prefill_kernel_matches_reference(B, H, Hk, T, starts, ctx_lens, window):
    """Flash prefill over the paged cache (VERDICT r3 item 2: the T>1
    path must stop falling back to the XLA group-expand reference)."""
    from dynamo_tpu.ops.paged_attention import paged_attention_prefill_stacked

    Dh, bs, num_blocks = 128, 16, 16
    rng = np.random.default_rng(11)
    _, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, ctx_lens)
    q = jnp.asarray(rng.standard_normal((B, T, H, Dh)).astype(np.float32))
    starts_a = jnp.asarray(starts, np.int32)
    out = paged_attention_prefill_stacked(
        q, k[None], v[None], jnp.int32(0), tables, starts_a, ctx, bs,
        sliding_window=window, interpret=True,
    )
    positions = starts_a[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    ref = paged_attention_reference(
        q, k, v, tables, positions, ctx, bs, window
    )
    # compare only REAL tokens (start + t < ctx); padded tokens are
    # discarded downstream (the reference NaN-masks differently)
    for b in range(B):
        n = max(0, int(ctx[b]) - int(starts[b]))
        n = min(n, T)
        if n == 0:
            continue
        np.testing.assert_allclose(
            np.asarray(out)[b, :n], np.asarray(ref)[b, :n],
            rtol=2e-2, atol=2e-2,
        )


def test_prefill_kernel_multi_tile():
    """T > tile size exercises the query-tile grid axis (tq=128)."""
    from dynamo_tpu.ops.paged_attention import paged_attention_prefill_stacked

    B, H, Hk, Dh, bs = 1, 2, 1, 128, 16
    T = 256  # two 128-token tiles
    num_blocks = 20
    rng = np.random.default_rng(3)
    _, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, [256])
    q = jnp.asarray(rng.standard_normal((B, T, H, Dh)).astype(np.float32))
    starts = jnp.zeros((B,), jnp.int32)
    out = paged_attention_prefill_stacked(
        q, k[None], v[None], jnp.int32(0), tables, starts, ctx, bs,
        interpret=True,
    )
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    ref = paged_attention_reference(q, k, v, tables, positions, ctx, bs)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize(
    "B,H,Hk,ctx_lens",
    [
        (2, 4, 2, [7, 29]),  # GQA, ragged contexts
        (1, 4, 4, [16]),  # MHA, exactly block-aligned
        (3, 8, 1, [1, 33, 5]),  # MQA, ctx=1 edge
        (2, 4, 2, [40, 0]),  # padded row (ctx=0)
    ],
)
def test_decode_kernel_matches_reference(B, H, Hk, ctx_lens):
    Dh, bs, num_blocks = 128, 16, 16
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, ctx_lens)
    out = paged_attention_decode(q, k, v, tables, ctx, bs, interpret=True)
    # reference wants [B, T, H, Dh] and per-token positions
    positions = jnp.maximum(ctx - 1, 0)[:, None]  # decode: last position
    ref = paged_attention_reference(
        q[:, None], k, v, tables, positions, ctx, bs
    )[:, 0]
    valid = np.asarray(ctx) > 0
    np.testing.assert_allclose(
        np.asarray(out)[valid], np.asarray(ref)[valid], rtol=2e-2, atol=2e-2
    )


def test_decode_kernel_bf16():
    Dh, bs, num_blocks = 128, 16, 8
    q, k, v, tables, ctx = _setup(2, 4, 2, Dh, num_blocks, bs, [12, 20])
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = paged_attention_decode(qb, kb, vb, tables, ctx, bs, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = paged_attention_reference(
        qb[:, None], kb, vb, tables, (ctx - 1)[:, None], ctx, bs
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        rtol=1e-1, atol=1e-1,
    )


@pytest.mark.parametrize("window,ctx_lens", [
    (8, [7, 29]),     # window < block_size
    (16, [40, 33]),   # window == block_size
    (24, [50, 3]),    # window spans pages; one ctx inside window
])
def test_decode_kernel_sliding_window(window, ctx_lens):
    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk = 2, 4, 2
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, ctx_lens)
    out = paged_attention_decode(
        q, k, v, tables, ctx, bs, sliding_window=window, interpret=True
    )
    positions = jnp.maximum(ctx - 1, 0)[:, None]
    ref = paged_attention_reference(
        q[:, None], k, v, tables, positions, ctx, bs, sliding_window=window
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2
    )


def test_decode_kernel_shard_map_tp():
    """The kernel under shard_map over a tp axis (attention is local per
    KV-head shard) matches the single-kernel result — the multi-device
    integration models/llama.py attend_mlp uses."""
    import functools

    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh

    from dynamo_tpu.utils.jaxtools import shard_map

    Dh, bs, num_blocks = 128, 16, 16
    B, H, Hk = 2, 8, 4
    q, k, v, tables, ctx = _setup(B, H, Hk, Dh, num_blocks, bs, [23, 37])
    mesh = build_mesh(MeshConfig(dp=2, tp=4), jax.devices())
    kern = functools.partial(
        paged_attention_decode, block_size=bs, interpret=True
    )
    wrapped = shard_map(
        kern,
        mesh=mesh,
        in_specs=(
            P(None, "tp", None), P(None, "tp", None), P(None, "tp", None),
            P(None, None), P(None),
        ),
        out_specs=P(None, "tp", None),
        axis_names={"tp"},
        check_vma=False,
    )
    out = jax.jit(wrapped)(q, k, v, tables, ctx)
    single = kern(q, k, v, tables, ctx)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(single), rtol=2e-2, atol=2e-2
    )


async def test_engine_tp_with_pallas_attention(monkeypatch):
    """Full engine on a tp=2 CPU mesh with the Pallas kernel forced
    (interpret) must match the reference-path engine's greedy tokens —
    the integration that unlocks fast attention on multi-chip ladders."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.llama import set_attention_mesh
    from tests.test_engine import MODEL_DIR, _generate

    cfg = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=32, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
        tensor_parallel_size=2,
    )
    prompt = list(range(1, 20))
    try:
        monkeypatch.setenv("DYN_ATTN_IMPL", "reference")
        eng = await JaxEngine.launch(EngineConfig(**cfg))
        try:
            ref_toks, _ = await _generate(eng, prompt, max_tokens=4)
        finally:
            await eng.shutdown()

        monkeypatch.setenv("DYN_ATTN_IMPL", "pallas")
        eng = await JaxEngine.launch(EngineConfig(**cfg))
        try:
            pal_toks, _ = await _generate(eng, prompt, max_tokens=4)
        finally:
            await eng.shutdown()
    finally:
        set_attention_mesh(None)
    assert pal_toks == ref_toks


async def test_engine_with_pallas_attention(monkeypatch):
    """Full engine decode through the kernel (interpret mode) must produce
    the same greedy tokens as the reference path."""
    import os

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from tests.test_engine import MODEL_DIR, _generate

    cfg = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=32, block_size=8, max_batch_size=4,
        prefill_chunk_size=32, max_model_len=128,
    )
    prompt = list(range(1, 20))

    monkeypatch.setenv("DYN_ATTN_IMPL", "reference")
    eng = await JaxEngine.launch(EngineConfig(**cfg))
    try:
        ref_toks, _ = await _generate(eng, prompt, max_tokens=4)
    finally:
        await eng.shutdown()

    monkeypatch.setenv("DYN_ATTN_IMPL", "pallas")
    eng = await JaxEngine.launch(EngineConfig(**cfg))
    try:
        pal_toks, _ = await _generate(eng, prompt, max_tokens=4)
    finally:
        await eng.shutdown()
    assert pal_toks == ref_toks
