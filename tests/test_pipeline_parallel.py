"""Pipeline-parallel forward vs the plain lax.scan forward.

Runs on the virtual 8-device CPU mesh (conftest). forward_pp must produce
identical logits and identical paged-KV cache contents (modulo the pad
slot 0, which bubble ticks scribble on by design).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import forward, init_cache, init_params
from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh
from dynamo_tpu.utils.jaxtools import partial_auto_shard_map_supported
from dynamo_tpu.parallel.pipeline import (
    PP_CACHE_SPEC,
    forward_pp,
    pp_param_specs,
)

BLOCK = 8


def _cfg(L=4):
    return ModelConfig(
        vocab_size=256, hidden_size=32, intermediate_size=64,
        num_hidden_layers=L, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
    )


def _step_args(cfg, B, T, n_blocks_per_seq, seed=0):
    from dynamo_tpu.utils.testing import make_paged_inputs

    return make_paged_inputs(cfg.vocab_size, B, T, BLOCK, n_blocks_per_seq, seed)


def _run_pp(pp, tp, B=4, T=16, L=4, microbatches=None):
    cfg = _cfg(L)
    mesh = build_mesh(
        MeshConfig(pp=pp, tp=tp), jax.devices()[: pp * tp]
    )
    params = init_params(cfg, seed=0)
    nbps = max(1, T // BLOCK)
    n_blocks = 1 + B * nbps  # block 0 is the pad/scratch block
    k_cache, v_cache = init_cache(cfg, num_blocks=n_blocks, block_size=BLOCK)
    args = _step_args(cfg, B, T, nbps)

    # single-device oracle
    ref_logits, ref_k, ref_v = forward(
        cfg, params, k_cache, v_cache, *args, BLOCK
    )

    # pp-sharded run
    specs = pp_param_specs(cfg)
    params_pp = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }
    cache_sh = NamedSharding(mesh, PP_CACHE_SPEC)
    k_pp = jax.device_put(k_cache, cache_sh)
    v_pp = jax.device_put(v_cache, cache_sh)
    with mesh:
        logits, new_k, new_v = jax.jit(
            lambda p, kc, vc, *a: forward_pp(
                cfg, p, kc, vc, *a, BLOCK, mesh,
                num_microbatches=microbatches,
            )
        )(params_pp, k_pp, v_pp, *args)
        jax.block_until_ready(logits)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=5e-2, atol=1e-1
    )
    # cache contents match everywhere except the pad block (slots 0..BLOCK)
    np.testing.assert_allclose(
        np.asarray(new_k)[:, BLOCK:], np.asarray(ref_k)[:, BLOCK:],
        rtol=5e-2, atol=5e-2,
    )
    np.testing.assert_allclose(
        np.asarray(new_v)[:, BLOCK:], np.asarray(ref_v)[:, BLOCK:],
        rtol=5e-2, atol=5e-2,
    )


def test_pp_only():
    _run_pp(pp=4, tp=1)


@pytest.mark.skipif(
    not partial_auto_shard_map_supported(),
    reason="pp x tp needs partial-auto shard_map (manual pp, auto tp); this jax's\n    experimental fallback lowers it to a PartitionId op XLA SPMD rejects\n    (UNIMPLEMENTED) — see ROADMAP open item 1",
)
def test_pp_times_tp():
    # tp=2 divides both H=4 and Hkv=2 in the test config
    _run_pp(pp=2, tp=2)


def test_pp_more_microbatches_than_stages():
    _run_pp(pp=2, tp=1, B=8, microbatches=4)


@pytest.mark.skipif(
    not partial_auto_shard_map_supported(),
    reason="pp x tp needs partial-auto shard_map (manual pp, auto tp); this jax's\n    experimental fallback lowers it to a PartitionId op XLA SPMD rejects\n    (UNIMPLEMENTED) — see ROADMAP open item 1",
)
def test_pp_decode_step():
    # T=1 decode: every microbatch is one token per sequence
    _run_pp(pp=2, tp=2, B=4, T=1, L=2)


@pytest.mark.skipif(
    not partial_auto_shard_map_supported(),
    reason="pp x tp needs partial-auto shard_map (manual pp, auto tp); this jax's\n    experimental fallback lowers it to a PartitionId op XLA SPMD rejects\n    (UNIMPLEMENTED) — see ROADMAP open item 1",
)
async def test_engine_serves_with_pipeline_parallelism():
    """A pp=2 x tp=2 engine must produce the same greedy tokens as the
    single-device engine for the same weights/config (the pp path is a
    distributed reformulation of the same forward)."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    mc = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=128,
    )

    async def run(pp: int, tp: int) -> list[int]:
        engine = await JaxEngine.launch(
            EngineConfig(
                model_path="", model_name="pp-test", random_weights=True,
                num_blocks=32, block_size=4, max_batch_size=4,
                pipeline_parallel_size=pp, tensor_parallel_size=tp,
                kv_cache_dtype="float32",
            ),
            model_config=mc,
        )
        req = PreprocessedRequest(
            request_id=f"pp{pp}", token_ids=list(range(1, 14)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=5, ignore_eos=True),
        )
        toks: list[int] = []
        async for item in engine.as_async_engine().generate(req, Context()):
            toks.extend(item.token_ids)
        await engine.shutdown()
        return toks

    base = await run(1, 1)
    pp_toks = await run(2, 2)
    assert base == pp_toks


async def test_engine_rejects_incompatible_pp():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    mc = ModelConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
    )
    with pytest.raises(ValueError, match="must divide"):
        await JaxEngine.launch(
            EngineConfig(model_path="", random_weights=True, num_blocks=8,
                         block_size=4, pipeline_parallel_size=3),
            model_config=mc,
        )
