"""Planner control-loop semantics (driven mode, no store/workers) and
the full-fleet simulator replays: watermark hysteresis, grace cycles,
clamping, SLO-triggered scaling, the degradation ladder, connector
refusals, self-healing reconciliation — and the ISSUE-6 acceptance
replay: ≥100k simulated requests with a composed seed-42 fault plan
(worker kill mid-burst), bit-identical across two runs, with the
planner restoring SLO attainment without human input."""

import logging
import time

import pytest

from dynamo_tpu.faults.plan import parse_plan
from dynamo_tpu.planner.planner import Planner, PlannerConfig, _Signal
from dynamo_tpu.sim import FleetSim, SimConfig, bursty_trace

# --- driven-planner harness -------------------------------------------------


class Grants:
    """Connector that grants (or refuses) and remembers the story."""

    def __init__(self, add_ok=True, remove_ok=True):
        self.add_ok = add_ok
        self.remove_ok = remove_ok
        self.calls = []

    async def add_component(self, component):
        self.calls.append(("add", component))
        return self.add_ok

    async def remove_component(self, component):
        self.calls.append(("remove", component))
        return self.remove_ok


class Hooks:
    def __init__(self):
        self.levels = []

    def set_level(self, level):
        self.levels.append(level)


def driven(config=None, conn=None, hooks=None, decode=1, prefill=0):
    conn = conn or Grants()
    planner = Planner(
        store=None, component=None, connector=conn,
        config=config or PlannerConfig(grace_cycles=2),
        decode_workers=decode, prefill_workers=prefill,
        degradation=hooks,
    )
    return planner, conn


def snap(kv=0.0, queue=0.0, slo=1.0, reporting=None, goodput=0.0):
    s = {
        "kv_load_mean": kv,
        "prefill_queue_depth": queue,
        "prefill_queue_per_worker": queue,
        "slo_attainment_mean": slo,
        "goodput_tokens_total": goodput,
    }
    if reporting is not None:
        s["decode_workers_reporting"] = float(reporting)
    return s


# --- watermarks, grace, clamping -------------------------------------------


async def test_watermark_hysteresis_band_is_quiet():
    planner, conn = driven()
    for _ in range(6):  # between the watermarks: no action ever
        await planner.make_adjustments(snap(kv=0.7))
    assert conn.calls == []
    assert planner.decode_workers == 1


async def test_grace_cycles_gate_scale_up_and_down():
    planner, conn = driven(decode=2)
    await planner.make_adjustments(snap(kv=0.95))
    assert conn.calls == []  # streak 1 < grace 2
    await planner.make_adjustments(snap(kv=0.95))
    assert conn.calls == [("add", "backend")]
    assert planner.decode_workers == 3
    # a breach interrupted by a healthy cycle starts over
    await planner.make_adjustments(snap(kv=0.95))
    await planner.make_adjustments(snap(kv=0.7))
    await planner.make_adjustments(snap(kv=0.95))
    assert planner.decode_workers == 3
    # sustained low load scales down after grace
    await planner.make_adjustments(snap(kv=0.1))
    await planner.make_adjustments(snap(kv=0.1))
    assert conn.calls[-1] == ("remove", "backend")
    assert planner.decode_workers == 2


async def test_min_max_clamping():
    cfg = PlannerConfig(grace_cycles=1, min_decode=1, max_decode=2,
                        degrade_max_level=0)
    planner, conn = driven(config=cfg, decode=2)
    for _ in range(4):
        await planner.make_adjustments(snap(kv=0.99))
    assert conn.calls == []  # already at max, ladder disabled
    planner2, conn2 = driven(config=cfg, decode=1)
    for _ in range(4):
        await planner2.make_adjustments(snap(kv=0.01))
    assert conn2.calls == []  # already at min


# --- SLO-aware scaling ------------------------------------------------------


async def test_slo_breach_scales_up_even_under_kv_watermark():
    cfg = PlannerConfig(grace_cycles=2, slo_target=0.9)
    planner, conn = driven(config=cfg)
    # memory-healthy (kv 0.3) but latency-sick (attainment 0.7)
    await planner.make_adjustments(snap(kv=0.3, slo=0.7))
    await planner.make_adjustments(snap(kv=0.3, slo=0.7))
    assert conn.calls == [("add", "backend")]
    assert planner.decode_workers == 2


async def test_scale_down_requires_slo_headroom():
    cfg = PlannerConfig(grace_cycles=2, slo_target=0.9, slo_headroom=0.05)
    planner, conn = driven(config=cfg, decode=3)
    # kv says shrink, but attainment sits inside the headroom band
    for _ in range(4):
        await planner.make_adjustments(snap(kv=0.1, slo=0.92))
    assert conn.calls == []
    # with real headroom the shrink proceeds
    await planner.make_adjustments(snap(kv=0.1, slo=0.97))
    await planner.make_adjustments(snap(kv=0.1, slo=0.97))
    assert conn.calls == [("remove", "backend")]


async def test_slo_disabled_keeps_pure_watermark_behavior():
    planner, conn = driven(decode=2)  # slo_target defaults to 0 (off)
    await planner.make_adjustments(snap(kv=0.1, slo=0.0))
    await planner.make_adjustments(snap(kv=0.1, slo=0.0))
    assert conn.calls == [("remove", "backend")]  # attainment ignored


# --- degradation ladder -----------------------------------------------------


async def test_ladder_escalates_at_max_capacity_and_relaxes_after():
    hooks = Hooks()
    cfg = PlannerConfig(grace_cycles=2, max_decode=1, slo_target=0.9)
    planner, conn = driven(config=cfg, hooks=hooks)
    for _ in range(4):  # two grace windows at max capacity, breaching
        await planner.make_adjustments(snap(kv=0.95, slo=0.5))
    assert conn.calls == []  # can't scale: degrade instead
    assert hooks.levels == [1, 2]
    assert planner.degradation_level == 2
    # headroom returns: unwind one rung per grace window
    for _ in range(4):
        await planner.make_adjustments(snap(kv=0.2, slo=1.0))
    assert hooks.levels == [1, 2, 1, 0]
    assert planner.degradation_level == 0


# --- connector refusals (satellite) ----------------------------------------


async def test_connector_refusal_resets_streak_and_rate_limits_warning(caplog):
    conn = Grants(add_ok=False)
    planner, _ = driven(config=PlannerConfig(grace_cycles=2), conn=conn)
    with caplog.at_level(logging.WARNING, logger="dynamo_tpu.planner"):
        for _ in range(5):
            await planner.make_adjustments(snap(kv=0.95))
    # refusals at cycle 2 and (after streak reset) cycle 4 — NOT 2,3,4,5
    assert [c for c in conn.calls if c[0] == "add"] == [
        ("add", "backend"), ("add", "backend"),
    ]
    assert planner.decode_workers == 1  # intent untouched by refusals
    warnings = [r for r in caplog.records if "connector refused" in r.message]
    assert len(warnings) == 1  # second refusal suppressed by the rate limit


# --- self-healing reconciliation (satellite) --------------------------------


async def test_reconciliation_replaces_externally_killed_worker():
    cfg = PlannerConfig(grace_cycles=99, reconcile_cycles=2)
    planner, conn = driven(config=cfg, decode=3)
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert conn.calls == []  # one missing cycle: not yet
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert conn.calls == [("add", "backend")]
    assert planner.decode_workers == 3  # replacement, not a scale-up
    assert planner.replacements_total == 1
    # once reporting recovers, the streak clears and nothing more happens
    await planner.make_adjustments(snap(kv=0.7, reporting=3))
    await planner.make_adjustments(snap(kv=0.7, reporting=3))
    assert len(conn.calls) == 1


async def test_reconciliation_replaces_multiple_missing_workers():
    cfg = PlannerConfig(grace_cycles=99, reconcile_cycles=1)
    planner, conn = driven(config=cfg, decode=4)
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert conn.calls == [("add", "backend")] * 2
    assert planner.replacements_total == 2


async def test_reconciliation_waits_out_replacement_provisioning():
    """A replacement the planner just ordered gets spawn_grace_cycles
    to start reporting; only after the grace expires is the spawn
    presumed dead and replaced again (no duplicate per slow spawn)."""
    cfg = PlannerConfig(grace_cycles=99, reconcile_cycles=2,
                        spawn_grace_cycles=4)
    planner, conn = driven(config=cfg, decode=3)
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert len(conn.calls) == 1  # replacement ordered at cycle 2
    # still not reporting, but within the provisioning grace: no dup
    for _ in range(3):
        await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert len(conn.calls) == 1
    # grace expired (cycle 6) -> presumed dead -> replaced again after
    # the reconcile streak re-accumulates
    for _ in range(3):
        await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert len(conn.calls) == 2
    assert planner.replacements_total == 2


async def test_scale_up_provisioning_does_not_look_like_a_loss():
    """Right after a scale-up, reporting < intent is spawn latency,
    not a dead worker — reconciliation must not order a duplicate."""
    cfg = PlannerConfig(grace_cycles=1, reconcile_cycles=1,
                        spawn_grace_cycles=5)
    planner, conn = driven(config=cfg, decode=1)
    await planner.make_adjustments(snap(kv=0.95, reporting=1))
    assert conn.calls == [("add", "backend")]  # scale-up, intent 2
    for _ in range(3):  # provisioning window: no spurious replacement
        await planner.make_adjustments(snap(kv=0.7, reporting=1))
    assert len(conn.calls) == 1
    # the worker comes up: credit clears, later losses detect normally
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    await planner.make_adjustments(snap(kv=0.7, reporting=1))
    assert conn.calls[-1] == ("add", "backend")
    assert planner.replacements_total == 1


async def test_reconciliation_drains_surplus_worker():
    """A spawn that lands after a scale-down already raced past it (or
    out-of-band capacity) leaves reporting > intent with no policy path
    to remove it — reconciliation drains it, one per sustained
    reconcile window, without touching intent."""
    cfg = PlannerConfig(grace_cycles=99, reconcile_cycles=2)
    planner, conn = driven(config=cfg, decode=2)
    await planner.make_adjustments(snap(kv=0.7, reporting=3))
    assert conn.calls == []  # one surplus cycle: not yet
    await planner.make_adjustments(snap(kv=0.7, reporting=3))
    assert conn.calls == [("remove", "backend")]
    assert planner.decode_workers == 2  # intent untouched
    # the drain landed: reporting matches intent, nothing more happens
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert len(conn.calls) == 1
    # a transient surplus (stale metrics for one cycle) never drains
    await planner.make_adjustments(snap(kv=0.7, reporting=3))
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert len(conn.calls) == 1


async def test_reconciliation_drains_surplus_at_min_decode():
    """The policy down-branch is clamped at min_decode, so only the
    reconciliation drain can ever remove a surplus there."""
    cfg = PlannerConfig(grace_cycles=99, reconcile_cycles=1, min_decode=1)
    planner, conn = driven(config=cfg, decode=1)
    await planner.make_adjustments(snap(kv=0.7, reporting=2))
    assert conn.calls == [("remove", "backend")]
    assert planner.decode_workers == 1


async def test_reconciliation_disabled_or_unreported_is_inert():
    planner, conn = driven(
        config=PlannerConfig(grace_cycles=99, reconcile_cycles=0), decode=3
    )
    for _ in range(5):
        await planner.make_adjustments(snap(kv=0.7, reporting=1))
    assert conn.calls == []
    planner2, conn2 = driven(
        config=PlannerConfig(grace_cycles=99, reconcile_cycles=1), decode=3
    )
    await planner2.make_adjustments(snap(kv=0.7))  # no reporting key at all
    assert conn2.calls == []


async def test_streak_survives_signal_reset_on_scale(caplog):
    """Scaling resets the watermark signal object; _Signal.observe math
    stays monotone around it."""
    sig = _Signal()
    sig.observe(up=True, down=False)
    sig.observe(up=True, down=False)
    assert sig.up_streak == 2 and sig.down_streak == 0
    sig.observe(up=False, down=True)
    assert sig.up_streak == 0 and sig.down_streak == 1


# --- degradation ladder wiring (planner/degradation.py) ---------------------


def test_ladder_policy_math_matches_rung_semantics():
    from dynamo_tpu.planner.degradation import LadderPolicy

    p = LadderPolicy(queue_factor=0.5, kv_factor=0.95, shed_queue_depth=8)
    assert p.admission_caps(100, 0.9, 0) == (100, 0.9)
    assert p.admission_caps(100, 0.9, 1) == (50, pytest.approx(0.855))
    assert p.admission_caps(100, 0.9, 2) == (50, pytest.approx(0.855))
    assert p.admission_caps(100, 0.9, 3) == (8, pytest.approx(0.855))
    assert p.admission_caps(1, 0.9, 1)[0] == 1  # floor, never zero
    # a disabled cap (0) stays disabled when tightened...
    assert p.admission_caps(0, 0.0, 1) == (0, 0.0)
    # ...except the rung-3 shed line, which imposes itself on the queue
    assert p.admission_caps(0, 0.0, 3) == (8, 0.0)
    assert [p.spec_enabled(True, lvl) for lvl in range(4)] == [
        True, True, False, False,
    ]
    assert not p.spec_enabled(False, 0)  # never re-enables a disabled base


def test_serving_degradation_applies_and_restores():
    from types import SimpleNamespace

    from dynamo_tpu.http.admission import AdmissionConfig, AdmissionController
    from dynamo_tpu.planner.degradation import ServingDegradation

    admission = AdmissionController(
        AdmissionConfig(max_queue_depth=100, max_kv_usage=0.9),
        load_fn=lambda: None,
    )
    engine = SimpleNamespace(spec_suspended=False)
    hooks = ServingDegradation(admission=admission, engine=engine)
    hooks.set_level(1)
    assert admission.config.max_queue_depth == 50
    assert not engine.spec_suspended
    hooks.set_level(2)
    assert engine.spec_suspended
    hooks.set_level(3)
    assert admission.config.max_queue_depth == 32
    assert admission.force_shed  # rung 3 binds even without load signals
    hooks.set_level(0)  # full unwind restores the base caps + spec
    assert admission.config.max_queue_depth == 100
    assert admission.config.max_kv_usage == pytest.approx(0.9)
    assert not admission.force_shed
    assert not engine.spec_suspended


def test_force_shed_sheds_signal_less_frontend_to_probe_trickle():
    """Rung 3 on a frontend with no load signal must NOT fail open:
    everything beyond the probe bucket gets 429 with reason=degraded."""
    from dynamo_tpu.http.admission import AdmissionConfig, AdmissionController

    t = [0.0]
    admission = AdmissionController(
        AdmissionConfig(probe_rate_per_s=1.0, probe_burst=1.0),
        load_fn=lambda: None,
        clock=lambda: t[0],
    )
    assert admission.check() is None  # fail-open by default
    admission.force_shed = True
    assert admission.check() is None  # the probe token
    rej = admission.check()
    assert rej is not None and rej.reason == "degraded"
    t[0] += 1.0  # bucket refills: the trickle keeps flowing
    assert admission.check() is None
    admission.force_shed = False
    assert admission.check() is None


async def test_store_degradation_publishes_the_rung():
    import asyncio
    import json

    from dynamo_tpu.planner.degradation import (
        StoreDegradation,
        degradation_key,
    )

    puts = []

    class FakeStore:
        async def kv_put(self, key, value, lease_id=0):
            puts.append((key, value))
            return 1

    StoreDegradation(FakeStore(), "dynamo").set_level(2)
    await asyncio.sleep(0)  # let the fire-and-forget publish task run
    assert len(puts) == 1
    key, value = puts[0]
    assert key == degradation_key("dynamo")
    body = json.loads(value)
    assert body["level"] == 2
    assert body["seq"] > 0  # ordering stamp for the watcher side


async def test_watch_degradation_follows_snapshot_and_events():
    import asyncio
    import json
    from types import SimpleNamespace

    from dynamo_tpu.planner.degradation import (
        ServingDegradation,
        degradation_key,
        watch_degradation,
    )
    from dynamo_tpu.store.base import KvEntry, WatchEvent

    key = degradation_key("dynamo")

    def entry(level, seq=None):
        body = {"level": level}
        if seq is not None:
            body["seq"] = seq
        return KvEntry(key, json.dumps(body).encode(), 1)

    events = asyncio.Queue()

    class FakeWatch:
        def snapshot(self):
            return [entry(1)]  # pre-existing rung applies immediately

        def __aiter__(self):
            return self

        async def __anext__(self):
            return await events.get()

    class FakeStore:
        async def watch_prefix(self, prefix):
            assert prefix == key
            return FakeWatch()

    engine = SimpleNamespace(spec_suspended=False)
    hooks = ServingDegradation(engine=engine)
    task = asyncio.get_running_loop().create_task(
        watch_degradation(FakeStore(), "dynamo", hooks)
    )
    try:
        await asyncio.sleep(0)
        assert hooks.level == 1  # from the snapshot
        await events.put(WatchEvent("put", entry(2)))
        await asyncio.sleep(0.01)
        assert hooks.level == 2 and engine.spec_suspended
        await events.put(WatchEvent("delete", entry(0)))
        await asyncio.sleep(0.01)
        assert hooks.level == 0 and not engine.spec_suspended
        # a put delayed behind a store reconnect must not overwrite a
        # newer rung: stale seq is ignored
        await events.put(WatchEvent("put", entry(3, seq=50)))
        await events.put(WatchEvent("put", entry(1, seq=40)))
        await asyncio.sleep(0.01)
        assert hooks.level == 3
    finally:
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task


# --- full sim replays -------------------------------------------------------


def _acceptance_run():
    """Bursty trace + composed seed-42 fault plan: a worker is killed
    mid-run while bursts keep landing; the planner must detect it via
    reconciliation and restore attainment. Returns (fleet, result)."""
    trace = bursty_trace(
        2800.0, seed=42, calm_rps=30.0, burst_rps=70.0,
        mean_calm_s=120.0, mean_burst_s=30.0,
    )
    plan = parse_plan("seed=42;worker.liveness:kill@after=1200")
    cfg = SimConfig(
        initial_decode=4, initial_prefill=2, max_queue_depth=150,
        slo_ttft_ms=3000.0, slo_itl_ms=60.0,
    )
    fleet = FleetSim(trace, cfg, plan=plan)
    fleet.attach_planner(PlannerConfig(
        adjustment_interval_s=20.0, grace_cycles=2, reconcile_cycles=2,
        slo_target=0.9, min_decode=2, max_decode=8,
        min_prefill=1, max_prefill=4,
    ))
    return fleet, fleet.run()


def test_sim_replay_100k_requests_recovers_slo_and_is_bit_identical():
    slo_target = 0.9
    t0 = time.monotonic()
    fleet_a, res_a = _acceptance_run()
    fleet_b, res_b = _acceptance_run()
    wall = time.monotonic() - t0
    # scale + budget: >=100k simulated requests, both replays in <30s
    assert res_a["requests"] >= 100_000, res_a["requests"]
    assert wall < 30.0, f"two replays took {wall:.1f}s"
    # the composed fault plan actually struck mid-run
    assert res_a["workers_killed"] == 1
    assert res_a["killed_inflight"] > 0
    kill_t = fleet_a.faults.fired[0][0]
    assert 0 < kill_t < 2800.0
    # self-healing: reconciliation replaced the worker without help
    assert res_a["planner"]["replacements"] >= 1
    # ... and SLO attainment came back to target afterwards: the
    # rolling window recovers within the post-kill horizon and holds
    # at the end of the run
    post_kill = [
        s["slo_attainment_mean"]
        for s in res_a["timeline"]
        if kill_t + 60.0 <= s["ts"] <= kill_t + 400.0
    ]
    assert post_kill and max(post_kill) >= slo_target
    assert res_a["final_window_attainment"] >= slo_target
    # deterministic replay: two runs at the same seed are BIT-identical,
    # timeline and all
    assert res_a == res_b


def test_acceptance_kill_attainment_improves_with_migration():
    """ISSUE-14 satellite: the same seed-42 mid-burst worker kill as the
    acceptance replay (shortened to bound wall time), migration on vs
    off — the sim and the live plane must agree that kill-recovery is
    better with mid-stream migration: the killed streams complete
    instead of scoring lost, and the post-kill attainment dip is no
    deeper."""
    # same seed-42 trace family/fleet as the acceptance replay, at a
    # load level with headroom: the kill (not burst shedding) is the
    # dominant SLO event in its window, so the migration delta is the
    # signal rather than noise under a saturation dip
    trace = bursty_trace(
        1400.0, seed=42, calm_rps=30.0, burst_rps=40.0,
        mean_calm_s=120.0, mean_burst_s=30.0,
    )

    def run(migration):
        plan = parse_plan("seed=42;worker.liveness:kill@after=1200")
        cfg = SimConfig(
            initial_decode=4, initial_prefill=2, max_queue_depth=150,
            slo_ttft_ms=3000.0, slo_itl_ms=60.0, migration=migration,
        )
        fleet = FleetSim(trace, cfg, plan=plan)
        fleet.attach_planner(PlannerConfig(
            adjustment_interval_s=20.0, grace_cycles=2, reconcile_cycles=2,
            slo_target=0.9, min_decode=2, max_decode=8,
            min_prefill=1, max_prefill=4,
        ))
        res = fleet.run()
        kill_t = fleet.faults.fired[0][0]
        dip = min(
            s["slo_attainment_mean"]
            for s in res["timeline"]
            if kill_t <= s["ts"] <= kill_t + 120.0
        )
        return res, dip

    res_on, dip_on = run(True)
    res_off, dip_off = run(False)
    # the kill struck both runs identically
    assert res_on["workers_killed"] == res_off["workers_killed"] == 1
    assert res_on["killed_inflight"] == res_off["killed_inflight"] > 0
    # migration converts losses into completions ...
    assert (
        res_on["resumed"] + res_on["refailed"] == res_on["killed_inflight"]
    )
    assert res_on["resumed"] > 0
    assert res_on["lost_inflight"] == 0
    assert res_off["lost_inflight"] == res_off["killed_inflight"]
    assert res_on["completed"] > res_off["completed"]
    assert res_on["met"] > res_off["met"]
    # ... attainment of OFFERED load improves (a policy can't score
    # this by rejecting traffic) ...
    assert (
        res_on["slo_attainment_offered"] > res_off["slo_attainment_offered"]
    )
    # ... and the rolling-window attainment dip right after the kill is
    # strictly shallower (the lost streams scored misses in the window)
    assert dip_on > dip_off


def test_sim_replay_scale_up_beats_frozen_fleet():
    """Sanity on the closed loop itself: the same overload trace with
    the planner frozen (min=max=initial) must do no better than the
    autoscaled run on goodput."""
    trace = bursty_trace(
        900.0, seed=7, calm_rps=40.0, burst_rps=80.0,
        mean_calm_s=90.0, mean_burst_s=45.0,
    )

    def run(autoscale):
        cfg = SimConfig(initial_decode=2, initial_prefill=1,
                        max_queue_depth=150, slo_ttft_ms=3000.0)
        fleet = FleetSim(trace, cfg)
        fleet.attach_planner(PlannerConfig(
            adjustment_interval_s=20.0, grace_cycles=2,
            slo_target=0.9, min_decode=2,
            max_decode=8 if autoscale else 2,
            min_prefill=1, max_prefill=4,
        ))
        return fleet.run()

    frozen = run(autoscale=False)
    scaled = run(autoscale=True)
    assert scaled["goodput_tokens"] > frozen["goodput_tokens"]
    # the loop actually scaled into the bursts (and back down after —
    # the run ends in a calm drain, so the FINAL count is small again)
    peak = max(
        s["decode_workers_reporting"] for s in scaled["timeline"]
    )
    assert peak > 2
