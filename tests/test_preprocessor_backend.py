"""Tests for tokenizer/preprocessor/backend (≈ reference lib/llm/tests/
{preprocessor,backend,tokenizers}.rs)."""

import os
from typing import Any, AsyncIterator

import pytest

from dynamo_tpu.backend import Backend, SequenceState, _longest_partial_suffix
from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    StopConditions,
)
from dynamo_tpu.protocols.openai import ChatCompletionRequest, CompletionRequest
from dynamo_tpu.runtime.engine import Context, FnEngine, collect
from dynamo_tpu.runtime.pipeline import build_pipeline
from dynamo_tpu.tokenizer import Tokenizer

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


@pytest.fixture(scope="module")
def tok() -> Tokenizer:
    return Tokenizer.from_file(MODEL_DIR)


def test_tokenizer_roundtrip(tok):
    ids = tok.encode("Hello, how are you?")
    assert tok.decode(ids) == "Hello, how are you?"
    assert 300 < tok.vocab_size <= 2048  # trained vocab ≤ model vocab (2048)


def test_decode_stream_incremental_matches_batch(tok):
    text = "The quick brown fox jumps over the lazy dog 123."
    ids = tok.encode(text)
    ds = tok.decode_stream(skip_special_tokens=True)
    streamed = "".join(filter(None, (ds.step(t) for t in ids)))
    assert streamed == tok.decode(ids, skip_special_tokens=True)


def test_decode_stream_multibyte_utf8(tok):
    """Multi-byte chars split across byte-fallback tokens must not emit
    replacement chars mid-stream."""
    text = "héllo wörld — ünïcode ✓"
    ids = tok.encode(text)
    ds = tok.decode_stream()
    parts = [p for p in (ds.step(t) for t in ids) if p]
    assert "�" not in "".join(parts)
    assert "".join(parts) == tok.decode(ids, skip_special_tokens=True)


def test_chat_template_render():
    fmt = PromptFormatter.from_model_dir(MODEL_DIR)
    out = fmt.render(
        [
            {"role": "system", "content": "You are helpful."},
            {"role": "user", "content": "Hi!"},
        ]
    )
    assert out == (
        "<|begin_of_text|><|start_header_id|>system<|end_header_id|>\n\n"
        "You are helpful.<|eot_id|><|start_header_id|>user<|end_header_id|>\n\n"
        "Hi!<|eot_id|><|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def test_template_raise_exception():
    fmt = PromptFormatter("{{ raise_exception('bad role') }}")
    from dynamo_tpu.preprocessor.prompt import TemplateError

    with pytest.raises(TemplateError):
        fmt.render([])


def test_partial_suffix_jail_logic():
    assert _longest_partial_suffix("hello <", ["</s>", "END"]) == 1
    assert _longest_partial_suffix("hello </s", ["</s>"]) == 3
    assert _longest_partial_suffix("hello", ["</s>"]) == 0
    assert _longest_partial_suffix("xEN", ["END", "ENDX"]) == 2


def test_sequence_state_stop_string_across_chunks(tok):
    """Stop string arriving split across token deltas is caught and jailed
    text before it is emitted, text after suppressed."""
    stop = "cd"  # will tokenize into pieces
    target = "ab" + stop + "XYZ"
    ids = tok.encode(target)
    state = SequenceState(
        decode=tok.decode_stream(),
        stop_strings=[stop],
        hidden_stop_ids=set(),
        max_tokens=None,
        min_tokens=None,
    )
    emitted = ""
    fin = None
    for t in ids:
        text, fin = state.step([t])
        emitted += text
        if fin:
            break
    assert fin == FinishReason.STOP
    assert emitted == "ab"


def make_token_engine(token_ids, finish="stop"):
    """Engine emitting given token ids one at a time (≈ echo_core)."""

    async def gen(request: Any, ctx: Context) -> AsyncIterator[Any]:
        for t in token_ids:
            if ctx.is_stopped:
                return
            yield LLMEngineOutput(request_id="r", token_ids=[t])
        yield LLMEngineOutput(request_id="r", finish_reason=FinishReason(finish))

    return FnEngine(gen)


async def test_backend_eos_hidden_stop(tok):
    eot = tok.token_to_id("<|eot_id|>")
    text_ids = tok.encode("hello world")
    engine = make_token_engine(text_ids + [eot] + tok.encode("IGNORED"))
    backend = Backend(tok, eos_token_ids=[eot])
    pipeline = build_pipeline(backend, engine)
    req = PreprocessedRequest(request_id="r", token_ids=[1, 2, 3])
    out = await collect(pipeline.generate(req, Context()))
    text = "".join(o.text or "" for o in out)
    assert text == "hello world"
    assert out[-1].finish_reason == FinishReason.STOP
    assert "IGNORED" not in text


async def test_backend_max_tokens(tok):
    ids = tok.encode("a b c d e f g h i j")
    engine = make_token_engine(ids)
    backend = Backend(tok)
    pipeline = build_pipeline(backend, engine)
    req = PreprocessedRequest(
        request_id="r", token_ids=[1], stop=StopConditions(max_tokens=3)
    )
    out = await collect(pipeline.generate(req, Context()))
    assert out[-1].finish_reason == FinishReason.LENGTH
    assert out[-1].completion_tokens == 3


async def test_backend_ignore_eos(tok):
    eot = tok.token_to_id("<|eot_id|>")
    ids = tok.encode("hello") + [eot] + tok.encode(" more")
    engine = make_token_engine(ids)
    backend = Backend(tok, eos_token_ids=[eot])
    pipeline = build_pipeline(backend, engine)
    req = PreprocessedRequest(
        request_id="r", token_ids=[1], stop=StopConditions(ignore_eos=True)
    )
    out = await collect(pipeline.generate(req, Context()))
    text = "".join(o.text or "" for o in out)
    assert "more" in text


async def test_full_openai_pipeline_chat(tok):
    """HTTP-shaped request through preprocessor → backend → engine and back
    to OpenAI chunks (≈ reference call stack §3.1)."""
    fmt = PromptFormatter.from_model_dir(MODEL_DIR)
    reply_ids = tok.encode("Hello there!")
    eot = tok.token_to_id("<|eot_id|>")

    captured = {}

    async def engine_gen(request: Any, ctx: Context) -> AsyncIterator[Any]:
        captured["request"] = request
        for t in reply_ids:
            yield LLMEngineOutput(request_id=request.request_id, token_ids=[t])
        yield LLMEngineOutput(request_id=request.request_id, token_ids=[eot])
        yield LLMEngineOutput(
            request_id=request.request_id, finish_reason=FinishReason.STOP
        )

    pre = OpenAIPreprocessor(tok, fmt, model_name="tiny")
    backend = Backend(tok, eos_token_ids=[eot])
    pipeline = build_pipeline(pre, backend, FnEngine(engine_gen))

    req = ChatCompletionRequest.model_validate(
        {
            "model": "tiny",
            "messages": [{"role": "user", "content": "Hi!"}],
            "stream": True,
            "stream_options": {"include_usage": True},
        }
    )
    chunks = await collect(pipeline.generate(req, Context()))
    # the engine saw the rendered+tokenized prompt
    sent = captured["request"]
    assert isinstance(sent, PreprocessedRequest)
    rendered = tok.decode(sent.token_ids, skip_special_tokens=False)
    assert "user" in rendered and "Hi!" in rendered
    # chunks rebuild the reply
    text = "".join(
        c.choices[0].delta.content or "" for c in chunks if c.choices
    )
    assert text == "Hello there!"
    # finish chunk, then (OpenAI stream_options semantics) a trailing
    # usage-only chunk with empty choices
    finish, usage = chunks[-2], chunks[-1]
    assert finish.choices[0].finish_reason == "stop"
    assert usage.choices == []
    assert usage.usage is not None and usage.usage.prompt_tokens == len(sent.token_ids)


async def test_full_openai_pipeline_completion(tok):
    reply_ids = tok.encode("42")
    pre = OpenAIPreprocessor(tok, None, model_name="tiny")
    backend = Backend(tok)
    pipeline = build_pipeline(pre, backend, make_token_engine(reply_ids))
    req = CompletionRequest.model_validate(
        {"model": "tiny", "prompt": "meaning of life = ", "max_tokens": 10}
    )
    chunks = await collect(pipeline.generate(req, Context()))
    text = "".join(c.choices[0].text for c in chunks if c.choices)
    assert text == "42"


def test_stop_string_earliest_occurrence_wins(tok):
    """With multiple stop strings, cut at the earliest occurrence in the
    text, not the first in list order."""
    state = SequenceState(
        decode=tok.decode_stream(),
        stop_strings=["END", "STOP"],
        hidden_stop_ids=set(),
        max_tokens=None,
        min_tokens=None,
    )
    emit, fin = state._apply_stop_strings("fooSTOPbarEND", past_min=True)
    assert emit == "foo"
    assert fin == FinishReason.STOP


async def test_backend_truncates_burst_at_stop():
    """Multi-token bursts (fused multi-step decode) must not leak tokens
    sampled past a hidden stop (EOS) to token-stream consumers."""
    from dynamo_tpu.backend import Backend
    from dynamo_tpu.protocols.common import (
        LLMEngineOutput,
        PreprocessedRequest,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.tokenizer import Tokenizer

    tok = Tokenizer.from_file(MODEL_DIR)
    backend = Backend(tok, eos_token_ids=[9])
    req = PreprocessedRequest(
        request_id="b1", token_ids=[1, 2],
        stop=StopConditions(max_tokens=32),
    )
    _, state = await backend.forward(req, Context())

    async def burst():
        # eos (9) at position 2 of an 8-token burst
        yield LLMEngineOutput(
            request_id="b1", token_ids=[11, 12, 9, 13, 14, 15, 16, 17],
            log_probs=[-0.1] * 8,
        )

    items = []
    async for out in backend.backward(burst(), state, Context()):
        items.append(out)
    emitted_ids = [t for it in items for t in it.token_ids]
    assert 9 not in emitted_ids  # hidden stop excluded
    assert emitted_ids == [11, 12]  # nothing past the stop
    final = items[-1]
    assert final.finish_reason is not None
    assert final.completion_tokens == 3  # eos consumed, not emitted
    for it in items:
        if it.log_probs:
            assert len(it.log_probs) == len(it.token_ids)
