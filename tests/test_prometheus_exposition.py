"""Prometheus exposition correctness for every /metrics surface
(ISSUE 2 satellite): the strict text-format parser in prom_parser.py
validates HELP/TYPE pairing, label escaping, series dedup, and
histogram invariants against REAL payloads served over HTTP by both
the OpenAI frontend and the metrics aggregation service."""

import asyncio
import json
from typing import Any, AsyncIterator

import aiohttp

from prom_parser import parse

from dynamo_tpu.http.service import HttpService, ModelManager
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatDeltaGenerator
from dynamo_tpu.runtime.engine import AsyncEngine, Context, EngineStream


class TinyEngine(AsyncEngine):
    async def _gen(self, request: Any, ctx: Context) -> AsyncIterator[Any]:
        gen = ChatDeltaGenerator(model=request.model)
        yield gen.text_chunk("hi ")
        yield gen.finish_chunk(FinishReason.STOP)

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._gen(request, context)


# unique model/404 names: the process registry is shared suite-wide, so
# assertions must scope to THIS test's label values
MODEL = "prom-expo-m"
MISSING = "prom-expo-nope"


async def _serve() -> tuple[HttpService, str]:
    manager = ModelManager()
    manager.add_chat_model(MODEL, TinyEngine())
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, f"http://127.0.0.1:{service.port}"


async def test_http_frontend_metrics_payload_well_formed():
    service, base = await _serve()
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": MODEL,
                "messages": [{"role": "user", "content": "x"}],
            }
            # drive every instrument: success, 404, streaming TTFT
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                assert r.status == 200
            async with s.post(
                f"{base}/v1/chat/completions",
                json={**payload, "model": MISSING},
            ) as r:
                assert r.status == 404
            async with s.post(
                f"{base}/v1/chat/completions", json={**payload, "stream": True}
            ) as r:
                assert r.status == 200
                await r.read()
            async with s.get(f"{base}/metrics") as r:
                assert r.status == 200
                text = await r.text()
        families = parse(text)  # raises on any malformation
        reqs = families["dynamo_http_requests_total"]
        assert reqs.type == "counter"
        by_status = {
            dict(k[1])["status"]: v for k, v in reqs.samples.items()
            if dict(k[1])["model"] in (MODEL, MISSING)
        }
        assert by_status.get("404") == 1
        assert families["dynamo_http_request_duration_seconds"].type == "histogram"
        # TTFT observed exactly once for this model (the streaming request)
        ttft = families["dynamo_http_time_to_first_token_seconds"]
        counts = [
            v for (name, labels), v in ttft.samples.items()
            if name.endswith("_count") and dict(labels)["model"] == MODEL
        ]
        assert counts == [1]
        # engine instruments are declared in the same registry and render
        # HELP/TYPE even with no series — still a valid payload
        assert "dynamo_engine_step_seconds" in families
    finally:
        await service.stop()


async def test_http_request_id_echoed_and_generated():
    """Satellite: X-Request-Id propagates (client's) or is generated."""
    service, base = await _serve()
    try:
        async with aiohttp.ClientSession() as s:
            payload = {
                "model": "m",
                "messages": [{"role": "user", "content": "x"}],
            }
            async with s.post(
                f"{base}/v1/chat/completions", json=payload,
                headers={"X-Request-Id": "client-rid-42"},
            ) as r:
                assert r.headers["X-Request-Id"] == "client-rid-42"
            async with s.post(f"{base}/v1/chat/completions", json=payload) as r:
                generated = r.headers["X-Request-Id"]
                assert len(generated) == 32  # uuid4 hex
            # errors echo it too
            async with s.post(
                f"{base}/v1/chat/completions",
                json={**payload, "model": "nope"},
                headers={"X-Request-Id": "rid-err"},
            ) as r:
                assert r.status == 404
                assert r.headers["X-Request-Id"] == "rid-err"
            # streaming responses carry the header on the SSE response
            async with s.post(
                f"{base}/v1/chat/completions",
                json={**payload, "stream": True},
                headers={"X-Request-Id": "rid-sse"},
            ) as r:
                assert r.headers["X-Request-Id"] == "rid-sse"
                await r.read()
    finally:
        await service.stop()


async def test_metrics_service_payload_well_formed():
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.metrics.service import MetricsService
    from dynamo_tpu.runtime.config import RuntimeConfig
    from dynamo_tpu.runtime.runtime import DistributedRuntime
    from dynamo_tpu.store.memory import MemoryStore
    from dynamo_tpu.store.server import StoreServer

    server = StoreServer(MemoryStore(), port=0)
    await server.start()
    drt = await DistributedRuntime.create(
        config=RuntimeConfig(store_port=server.port, worker_host="127.0.0.1")
    )
    comp = drt.namespace("promns").component("backend")
    svc = MetricsService(comp, host="127.0.0.1", port=0)
    await svc.start()
    try:
        # two workers, one with a label-escaping-hostile id is impossible
        # (ids are ints), so exercise the multi-series path instead
        for wid, usage in ((0xAB, 0.5), (0xCD, 0.25)):
            svc.aggregator.update(
                ForwardPassMetrics(
                    worker_id=wid, gpu_cache_usage_perc=usage,
                    kv_active_blocks=4, kv_total_blocks=8,
                    request_active_slots=1, request_total_slots=2,
                )
            )
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{svc.port}/metrics") as r:
                assert r.status == 200
                text = await r.text()
        families = parse(text)
        workers = families["llm_worker_kv_cache_usage"]
        assert {dict(k[1])["worker"] for k in workers.samples} == {"ab", "cd"}
        assert families["llm_kv_blocks_active"].samples[
            ("llm_kv_blocks_active", ())
        ] == 8.0
        # a worker aging out of the snapshot drops from the payload
        svc.aggregator.metrics.clear()
        families2 = parse(svc.render())
        assert not families2["llm_worker_kv_cache_usage"].samples
    finally:
        await svc.close()
        await drt.shutdown()
        await server.stop()


def test_parser_rejects_malformed_payloads():
    import pytest

    # samples before TYPE
    with pytest.raises(ValueError):
        parse("x_total 1\n# HELP x_total h\n# TYPE x_total counter\n")
    # duplicate series
    with pytest.raises(ValueError, match="duplicate series"):
        parse(
            "# HELP x_total h\n# TYPE x_total counter\n"
            'x_total{a="1"} 1\nx_total{a="1"} 2\n'
        )
    # non-contiguous family
    with pytest.raises(ValueError):
        parse(
            "# HELP a h\n# TYPE a gauge\na 1\n"
            "# HELP b h\n# TYPE b gauge\nb 1\na 2\n"
        )
    # bad escape
    with pytest.raises(ValueError, match="escape"):
        parse('# HELP x h\n# TYPE x gauge\nx{l="a\\q"} 1\n')
    # histogram +Inf/count mismatch
    with pytest.raises(ValueError, match="\\+Inf"):
        parse(
            "# HELP h h\n# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 2\nh_sum 1\nh_count 3\n'
        )
