"""Tests for the protocols layer (≈ reference lib/llm/tests/{openai_completions,aggregators}.rs)."""

import json

from dynamo_tpu.protocols.aggregators import ChatAggregator, CompletionAggregator
from dynamo_tpu.protocols.annotated import Annotated
from dynamo_tpu.protocols.common import FinishReason
from dynamo_tpu.protocols.openai import (
    ChatCompletionRequest,
    ChatDeltaGenerator,
    CompletionDeltaGenerator,
    CompletionRequest,
    ExtOptions,
    Usage,
)
from dynamo_tpu.protocols.sse import SseDecoder, encode_done, encode_sse


def test_chat_request_adaptation():
    req = ChatCompletionRequest.model_validate(
        {
            "model": "llama",
            "messages": [{"role": "user", "content": "hi"}],
            "temperature": 0.0,
            "max_tokens": 7,
            "stop": "END",
            "ext": {"ignore_eos": True, "top_k": 5},
        }
    )
    s = req.sampling_options()
    assert s.use_greedy is True and s.temperature is None and s.top_k == 5
    sc = req.stop_conditions()
    assert sc.max_tokens == 7 and sc.stop == ["END"] and sc.ignore_eos


def test_nvext_alias_accepted():
    req = ChatCompletionRequest.model_validate(
        {
            "model": "m",
            "messages": [{"role": "user", "content": "x"}],
            "nvext": {"greedy_sampling": True},
        }
    )
    assert req.extension().greedy_sampling is True


def test_multimodal_content_parts():
    req = ChatCompletionRequest.model_validate(
        {
            "model": "m",
            "messages": [
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "describe "},
                        {"type": "image_url", "image_url": {"url": "http://x/y.png"}},
                        {"type": "text", "text": "this"},
                    ],
                }
            ],
        }
    )
    assert req.messages[0].text_content() == "describe this"


def test_completion_prompt_forms():
    for prompt in ["abc", ["a", "b"], [1, 2, 3], [[1, 2], [3]]]:
        req = CompletionRequest.model_validate({"model": "m", "prompt": prompt})
        assert req.prompt == prompt


def test_sse_roundtrip():
    wire = encode_sse({"a": 1}, event="delta", id="7", comments=["keepalive"])
    wire += encode_sse("plain text")
    wire += encode_done()
    dec = SseDecoder()
    msgs = []
    # feed in awkward chunk sizes to exercise incremental parsing
    for i in range(0, len(wire), 7):
        msgs.extend(dec.feed(wire[i : i + 7]))
    assert len(msgs) == 3
    assert msgs[0].event == "delta" and msgs[0].json() == {"a": 1}
    assert msgs[0].comments == ["keepalive"] and msgs[0].id == "7"
    assert msgs[1].data == "plain text"
    assert msgs[2].is_done


def test_sse_multiline_data():
    wire = encode_sse("line1\nline2")
    dec = SseDecoder()
    (msg,) = list(dec.feed(wire))
    assert msg.data == "line1\nline2"


def test_annotated_envelope():
    a = Annotated.from_data({"x": 1})
    assert not a.is_error
    e = Annotated.from_error("boom")
    assert e.is_error and e.error_message() == "boom"
    ann = Annotated.from_annotation("ttft_ms", 12.5)
    assert ann.event == "ttft_ms" and json.loads(ann.comment[0]) == 12.5


def test_chat_delta_stream_and_aggregate():
    gen = ChatDeltaGenerator(model="llama")
    chunks = [
        gen.text_chunk("Hel"),
        gen.text_chunk("lo"),
        gen.finish_chunk(FinishReason.STOP),
        gen.usage_chunk(Usage(prompt_tokens=3, completion_tokens=2, total_tokens=5)),
    ]
    # first chunk carries the role
    assert chunks[0].choices[0].delta.role == "assistant"
    assert chunks[1].choices[0].delta.role is None
    resp = ChatAggregator.aggregate(chunks)
    assert resp.choices[0].message.content == "Hello"
    assert resp.choices[0].finish_reason == "stop"
    assert resp.usage.total_tokens == 5
    assert resp.id == gen.id


def test_completion_delta_stream_and_aggregate():
    gen = CompletionDeltaGenerator(model="llama")
    chunks = [gen.text_chunk("a"), gen.text_chunk("b"), gen.finish_chunk("length")]
    resp = CompletionAggregator.aggregate(chunks)
    assert resp.choices[0].text == "ab"
    assert resp.choices[0].finish_reason == "length"


def test_finish_reason_wire_mapping():
    gen = ChatDeltaGenerator(model="m")
    c = gen.finish_chunk(FinishReason.CANCELLED)
    assert c.choices[0].finish_reason == "stop"  # OpenAI wire has no 'cancelled'


def test_ext_extra_fields_allowed():
    ext = ExtOptions.model_validate({"ignore_eos": True, "custom_field": 42})
    assert ext.ignore_eos and ext.model_extra["custom_field"] == 42
