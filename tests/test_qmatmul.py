"""Fused int8 dequant-matmul kernels (ops/qmatmul.py): numerics vs the
reference ``mm()`` path, every fused epilogue variant, the engine-level
greedy bit-identity contract (DYN_MATMUL_IMPL=reference vs =pallas in
interpret mode — ISSUE 9 acceptance), and the autotune table's
roundtrip / corruption-degrades-to-default behavior.

All kernel calls run ``interpret=True`` (tier-1 is CPU); the engine
tests register a size-1 mesh through JaxEngine.launch so
``pallas_matmul_active()`` holds exactly as it does on a single chip.
"""

import asyncio
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.ops import qmatmul
from dynamo_tpu.ops.qmatmul import (
    default_tiles,
    m_bucket,
    qmm,
    qmm_gate_up,
    qmm_lm_head,
    record_tiles,
    tile_config,
)

RNG = np.random.default_rng(7)


def _mk(m, k, n, dtype=jnp.bfloat16, lead=()):
    x = jnp.asarray(RNG.standard_normal((*lead, m, k)), dtype)
    w = jnp.asarray(RNG.integers(-127, 128, (k, n)), jnp.int8)
    s = jnp.asarray(RNG.uniform(0.001, 0.02, n), jnp.float32)
    return x, w, s


def _ref_mm(x, w, s):
    """The reference mm() epilogue: mixed dot, f32 accumulate, scale in
    f32, round to the activation dtype."""
    y = jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (y * s).astype(x.dtype)


# ---------------------------------------------------------------------------
# Kernel numerics
# ---------------------------------------------------------------------------


def test_qmm_f32_epilogue_exact_single_k_tile():
    """With one K tile there is no accumulation-order freedom: the int8
    upcast, the f32 products, and the f32 scale multiply must be EXACT
    against the reference dot (int8 -> float is lossless, products of
    floats are exact in f32 preferred-type accumulation)."""
    x, w, s = _mk(5, 64, 256, dtype=jnp.float32)
    y = qmm(x, w, s, interpret=True)  # K=64 -> bk=K (single tile)
    ref = _ref_mm(x, w, s)
    assert y.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(y), np.asarray(ref))


def test_qmm_bf16_within_tolerance_tiled_k():
    """Forced multi-tile K: only accumulation ORDER differs from the
    reference, so the bf16 outputs may differ by at most ~1 ulp."""
    x, w, s = _mk(33, 512, 384)
    y = qmm(x, w, s, interpret=True, tiles=(64, 128, 128))
    ref = _ref_mm(x, w, s)
    a, b = np.asarray(y, np.float32), np.asarray(ref, np.float32)
    # 1 bf16 ulp at the observed magnitudes (~|x| <= 8 here)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=6e-2)
    assert y.shape == (33, 384)  # padded rows sliced back off


def test_qmm_leading_batch_dims():
    x, w, s = _mk(6, 64, 128, lead=(3,))
    y = qmm(x, w, s, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(y, np.float32), np.asarray(_ref_mm(x, w, s), np.float32)
    )


def test_qmm_residual_epilogue_matches_reference_rounding():
    """residual + (acc*scale).astype(dtype): the add happens in the
    OUTPUT dtype, exactly like the reference ``x + mm(...).astype``
    composition — single K tile makes it bit-exact."""
    x, w, s = _mk(8, 128, 256)
    r = jnp.asarray(RNG.standard_normal((8, 256)), jnp.bfloat16)
    y = qmm(x, w, s, residual=r, interpret=True)
    ref = r + _ref_mm(x, w, s)
    np.testing.assert_array_equal(
        np.asarray(y, np.float32), np.asarray(ref, np.float32)
    )


@pytest.mark.parametrize("act", ["silu", "gelu"])
def test_qmm_gate_up_fused(act):
    """act(x@Wg*sg) * (x@Wu*su) with both matmul outputs rounded to the
    activation dtype BEFORE the activation — the reference
    ``mlp_act(mm(gate)) * mm(up)`` rounding points."""
    x, wg, sg = _mk(8, 128, 256)
    _, wu, su = _mk(8, 128, 256)
    y = qmm_gate_up(x, wg, sg, wu, su, act=act, interpret=True)
    g, u = _ref_mm(x, wg, sg), _ref_mm(x, wu, su)
    ref = (
        jax.nn.gelu(g, approximate=True) if act == "gelu" else jax.nn.silu(g)
    ) * u
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_qmm_gate_up_rejects_unknown_act():
    x, wg, sg = _mk(8, 128, 128)
    with pytest.raises(ValueError, match="unsupported activation"):
        qmm_gate_up(x, wg, sg, wg, sg, act="relu6", interpret=True)


def test_qmm_lm_head_vocab_tiled():
    """The vocab-tiled variant over a non-power-of-two N that only a
    subset of tile widths divide (128256 = 167 * 768 — the real
    flagship vocab's divisibility structure, scaled down)."""
    V = 768 * 3
    x, w, s = _mk(4, 64, V)
    y = qmm_lm_head(x, w, s, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(y, np.float32), np.asarray(_ref_mm(x, w, s), np.float32)
    )


# ---------------------------------------------------------------------------
# Tile selection + autotune table
# ---------------------------------------------------------------------------


def test_m_bucket_monotonic():
    assert m_bucket(1) == 8
    assert m_bucket(8) == 8
    assert m_bucket(9) == 16
    assert m_bucket(64) == 64
    # beyond the ladder the bucket rounds UP (rounding down would make
    # the pad width negative and crash the wrapper)
    top = qmatmul.M_BUCKETS[-1]
    assert m_bucket(top + 1) == 2 * top
    assert m_bucket(3 * top) == 3 * top


def test_qmm_m_above_largest_bucket():
    """M past the bucket ladder (e.g. a wide prefill rectangle) must
    compute, not crash on a negative pad."""
    top = qmatmul.M_BUCKETS[-1]
    x, w, s = _mk(top + 3, 64, 128, dtype=jnp.float32)
    y = qmm(x, w, s, interpret=True)
    assert y.shape == (top + 3, 128)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(_ref_mm(x, w, s)))


def test_qmm_rejects_non_dividing_explicit_tiles():
    """The explicit `tiles` kwarg bypasses table validation; a blocking
    that doesn't divide the problem must fail loudly (a silent floor-
    divided grid would leave output columns unwritten)."""
    x, w, s = _mk(8, 256, 256)
    with pytest.raises(ValueError, match="must divide"):
        qmm(x, w, s, interpret=True, tiles=(8, 200, 256))


@pytest.mark.parametrize(
    "mb,K,N,kind",
    [
        (64, 4096, 4096, "mm"),
        (64, 4096, 1024, "mm"),
        (64, 4096, 14336, "gate_up"),
        (64, 14336, 4096, "residual"),
        (64, 4096, 128256, "lm_head"),
        (8, 64, 96, "mm"),  # tiny/odd: full-dim fallbacks
    ],
)
def test_default_tiles_always_legal(mb, K, N, kind):
    bm, bn, bk = default_tiles(mb, K, N, kind)
    assert mb % bm == 0 and N % bn == 0 and K % bk == 0
    assert bn == N or bn % 128 == 0
    assert bk == K or bk % 128 == 0


def test_lm_head_tiles_divide_flagship_vocab():
    # 128256 is not divisible by 512; the lm_head candidate ladder must
    # land on a divisor (768), not crash or fall back to full-V tiles
    _, bn, _ = default_tiles(64, 4096, 128256, "lm_head")
    assert 128256 % bn == 0 and bn >= 256


@pytest.fixture
def tune_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DYN_QMATMUL_TUNE_DIR", str(tmp_path))
    qmatmul._reset_table_for_tests()
    yield tmp_path
    qmatmul._reset_table_for_tests()


def test_tune_table_roundtrip(tune_dir):
    record_tiles(48, 512, 768, "mm", (64, 256, 128))
    # fresh process simulation: drop the in-memory table, reload disk
    qmatmul._reset_table_for_tests()
    assert tile_config(48, 512, 768, "mm") == (64, 256, 128)
    # a different key still gets the heuristic default
    assert tile_config(48, 512, 384, "mm") == default_tiles(64, 512, 384, "mm")
    data = json.loads((tune_dir / "tune.json").read_text())
    assert data["version"] == 1 and "mm:64:512:768" in data["entries"]


def test_tune_table_corruption_degrades_to_default(tune_dir):
    (tune_dir / "tune.json").write_text("{not json")
    qmatmul._reset_table_for_tests()
    assert tile_config(64, 512, 768, "mm") == default_tiles(64, 512, 768, "mm")
    # structurally-valid JSON with a poisoned entry: the entry must be
    # rejected by validation, not fed to the kernel
    (tune_dir / "tune.json").write_text(json.dumps({
        "version": 1,
        "entries": {
            "mm:64:512:768": [7, 100, 3],      # divides nothing
            "mm:64:512:384": "garbage",          # wrong type
            "mm:64:512:256": [64, 128],          # wrong arity
        },
    }))
    qmatmul._reset_table_for_tests()
    assert tile_config(64, 512, 768, "mm") == default_tiles(64, 512, 768, "mm")
    assert tile_config(64, 512, 384, "mm") == default_tiles(64, 512, 384, "mm")
    assert tile_config(64, 512, 256, "mm") == default_tiles(64, 512, 256, "mm")


def test_ensure_tuned_off_tpu_is_read_only(tune_dir):
    """ensure_tuned without DYN_QMATMUL_TUNE resolves configs but never
    writes (no autotune off-TPU; the cache stays whatever it was)."""
    qmatmul.ensure_tuned([(64, 512, 768, "mm"), (64, 512, 384, "gate_up")])
    assert not (tune_dir / "tune.json").exists()


def test_tuned_entry_used_by_kernel(tune_dir):
    """A (valid) tuned entry actually drives the kernel blocking and
    produces the same numbers as the default blocking."""
    record_tiles(8, 256, 256, "mm", (8, 128, 128))
    qmatmul._reset_table_for_tests()
    x, w, s = _mk(8, 256, 256)
    y = qmm(x, w, s, interpret=True)
    ref = _ref_mm(x, w, s)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref, np.float32),
        rtol=2e-2, atol=6e-2,
    )


# ---------------------------------------------------------------------------
# Model-level dispatch + engine greedy bit-identity (the acceptance gate)
# ---------------------------------------------------------------------------


def test_matmul_impl_dispatch(monkeypatch):
    from dynamo_tpu.models import llama

    monkeypatch.setenv("DYN_MATMUL_IMPL", "reference")
    assert llama.matmul_impl() == "reference"
    assert not llama.pallas_matmul_active()
    monkeypatch.setenv("DYN_MATMUL_IMPL", "pallas")
    assert llama.matmul_impl() == "pallas"
    monkeypatch.delenv("DYN_MATMUL_IMPL")
    # auto off-TPU = reference (kernels only via explicit opt-in here)
    assert llama.matmul_impl() == "reference"


async def _engine_tokens(model_cfg, decode_steps: int) -> list[int]:
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    engine = await JaxEngine.launch(
        EngineConfig(
            model_path="", model_name="qmm", random_weights=True,
            quantization="int8", num_blocks=64, block_size=8,
            max_batch_size=4, decode_steps=decode_steps,
            kv_cache_dtype="int8",
        ),
        model_config=model_cfg,
    )
    try:
        req = PreprocessedRequest(
            request_id="q", token_ids=list(range(1, 20)),
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
        )
        toks: list[int] = []
        async for out in engine.as_async_engine().generate(req, Context()):
            toks.extend(out.token_ids)
        return toks
    finally:
        await engine.shutdown()


@pytest.mark.parametrize("decode_steps", [1, 2])
def test_engine_greedy_bit_identical_reference_vs_pallas(
    decode_steps, monkeypatch
):
    """ISSUE 9 acceptance: the engine's greedy output is bit-identical
    between DYN_MATMUL_IMPL=reference and =pallas (interpret mode on
    CPU), over the int8 KV cache, on both the single-step (overlapped
    pipeline) and fused-window decode paths."""
    from dynamo_tpu.models.config import ModelConfig

    mc = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256,
    )
    monkeypatch.setenv("DYN_MATMUL_IMPL", "reference")
    ref = asyncio.run(_engine_tokens(mc, decode_steps))
    monkeypatch.setenv("DYN_MATMUL_IMPL", "pallas")
    pal = asyncio.run(_engine_tokens(mc, decode_steps))
    assert ref == pal
    assert len(ref) == 10
