"""Weight-only int8 quantization (models/quant.py): numerics, loader
integration, forward parity, and the quantized serving engine.

Reference parity note: the reference serves quantized checkpoints through
its engines (FP8-dynamic models in examples/llm/benchmarks/README.md);
here quantization is a first-class engine knob."""

import asyncio
import json
import os

import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.quant import (
    QUANT_AXIS,
    quantize_array,
    quantize_params_pytree,
    scale_spec,
)

TINY = ModelConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128,
    num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
    max_position_embeddings=256,
)


def test_quantize_array_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 128)).astype(np.float32)
    q, s = quantize_array(w, -2)
    assert q.dtype == np.int8 and s.shape == (128,)
    deq = q.astype(np.float32) * s
    # symmetric per-channel int8: max error is half a quant step
    step = np.abs(w).max(axis=0) / 127.0
    assert np.all(np.abs(deq - w) <= step / 2 + 1e-7)


def test_quantize_array_bf16_uint16_input():
    import jax.numpy as jnp

    w = np.asarray(jnp.asarray([[1.5, -2.0], [0.25, 3.0]], jnp.bfloat16))
    assert w.dtype == np.uint16 or w.dtype.name == "bfloat16"
    raw = np.asarray(jnp.asarray(w).view(jnp.uint16)) if w.dtype.name == "bfloat16" else w
    q, s = quantize_array(raw, -2)
    deq = q.astype(np.float32) * s
    np.testing.assert_allclose(deq, [[1.5, -2.0], [0.25, 3.0]], rtol=0.02)


def test_scale_spec_drops_contraction_axis():
    from jax.sharding import PartitionSpec as P

    assert scale_spec(P(None, None, "tp"), -2) == P(None, "tp")
    assert scale_spec(P(None, "tp", None), -2) == P(None, None)
    assert scale_spec(P("tp", None), -1) == P("tp")
    assert scale_spec(P(None, "ep", None, "tp"), -2) == P(None, "ep", "tp")


def _forward_logits(cfg, params, prompt):
    import jax.numpy as jnp

    from dynamo_tpu.models.llama import forward, init_cache

    T = len(prompt)
    k_cache, v_cache = init_cache(cfg, num_blocks=32, block_size=8)
    tokens = jnp.asarray([prompt], jnp.int32)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    slot_mapping = jnp.arange(T, dtype=jnp.int32) + 8  # blocks 1..
    block_tables = (jnp.arange(8, dtype=jnp.int32) + 1)[None, :]
    context_lens = jnp.asarray([T], jnp.int32)
    last_idx = jnp.asarray([T - 1], jnp.int32)
    logits, _, _ = forward(
        cfg, params, k_cache, v_cache, tokens, positions, slot_mapping,
        block_tables, context_lens, last_idx, 8,
    )
    return np.asarray(logits[0], np.float32)


def test_forward_parity_bf16_vs_int8():
    """Quantized logits must track the bf16 forward closely (the CI
    numerics bound quant.py's docstring promises)."""
    from dynamo_tpu.models.llama import init_params

    params = init_params(TINY, seed=3)
    qparams = quantize_params_pytree(params)
    assert qparams["wq"].dtype.name == "int8"
    assert "wq_scale" in qparams and "embed_scale" in qparams
    prompt = list(range(7, 27))
    ref = _forward_logits(TINY, params, prompt)
    got = _forward_logits(TINY, qparams, prompt)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 0.05, f"relative logits error {rel:.4f}"


def test_forward_parity_moe_int8():
    cfg = ModelConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128,
    )
    from dynamo_tpu.models.llama import init_params

    params = init_params(cfg, seed=5)
    qparams = quantize_params_pytree(params)
    assert qparams["w_gate"].dtype.name == "int8"
    assert qparams["w_gate_scale"].shape == (2, 4, 64)
    prompt = list(range(3, 19))
    ref = _forward_logits(cfg, params, prompt)
    got = _forward_logits(cfg, qparams, prompt)
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 0.08, f"relative logits error {rel:.4f}"


def _write_tiny_checkpoint(cfg, path, tied=False, seed=0):
    """HF-format safetensors checkpoint with random weights."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    H, Hk, Dh, L = (cfg.num_attention_heads, cfg.num_key_value_heads,
                    cfg.head_dim, cfg.num_hidden_layers)
    t = {}
    t["model.embed_tokens.weight"] = rng.standard_normal((V, D)).astype(np.float32)
    t["model.norm.weight"] = np.ones((D,), np.float32)
    if not tied:
        t["lm_head.weight"] = rng.standard_normal((V, D)).astype(np.float32)
    for i in range(L):
        p = f"model.layers.{i}"
        t[f"{p}.input_layernorm.weight"] = np.ones((D,), np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = np.ones((D,), np.float32)
        t[f"{p}.self_attn.q_proj.weight"] = rng.standard_normal((H * Dh, D)).astype(np.float32) * 0.1
        t[f"{p}.self_attn.k_proj.weight"] = rng.standard_normal((Hk * Dh, D)).astype(np.float32) * 0.1
        t[f"{p}.self_attn.v_proj.weight"] = rng.standard_normal((Hk * Dh, D)).astype(np.float32) * 0.1
        t[f"{p}.self_attn.o_proj.weight"] = rng.standard_normal((D, H * Dh)).astype(np.float32) * 0.1
        t[f"{p}.mlp.gate_proj.weight"] = rng.standard_normal((F, D)).astype(np.float32) * 0.1
        t[f"{p}.mlp.up_proj.weight"] = rng.standard_normal((F, D)).astype(np.float32) * 0.1
        t[f"{p}.mlp.down_proj.weight"] = rng.standard_normal((D, F)).astype(np.float32) * 0.1
    os.makedirs(path, exist_ok=True)
    save_file(t, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({
            "model_type": "llama", "vocab_size": V, "hidden_size": D,
            "intermediate_size": F, "num_hidden_layers": L,
            "num_attention_heads": H, "num_key_value_heads": Hk,
            "max_position_embeddings": cfg.max_position_embeddings,
            "tie_word_embeddings": tied,
        }, f)
    return t


def test_loader_quantized_matches_host_quantization(tmp_path):
    from dynamo_tpu.models.loader import load_params

    t = _write_tiny_checkpoint(TINY, str(tmp_path))
    params = load_params(TINY, str(tmp_path), quantize="int8")
    for name in ("wq", "wo", "w_down", "lm_head", "embed"):
        assert params[name].dtype.name == "int8", name
        assert name + "_scale" in params
    # spot-check one weight against direct host quantization
    w0 = t["model.layers.0.self_attn.q_proj.weight"].T  # [D, H*Dh]
    q, s = quantize_array(w0, -2)
    np.testing.assert_array_equal(np.asarray(params["wq"])[0], q)
    np.testing.assert_allclose(np.asarray(params["wq_scale"])[0], s)
    # norms stay f32
    assert params["attn_norm"].dtype.name == "float32"


def test_loader_quantized_tied_lm_head(tmp_path):
    from dynamo_tpu.models.loader import load_params

    cfg = ModelConfig(**{**TINY.__dict__, "tie_word_embeddings": True})
    cfg.head_dim = None
    cfg.__post_init__()
    _write_tiny_checkpoint(cfg, str(tmp_path), tied=True)
    params = load_params(cfg, str(tmp_path), quantize="int8")
    np.testing.assert_array_equal(
        np.asarray(params["lm_head"]), np.asarray(params["embed"]).T
    )
    np.testing.assert_array_equal(
        np.asarray(params["lm_head_scale"]), np.asarray(params["embed_scale"])
    )


def test_checkpoint_quantized_forward_parity(tmp_path):
    """End-to-end: checkpoint -> (bf16 load, int8 load) -> close logits."""
    from dynamo_tpu.models.loader import load_params

    _write_tiny_checkpoint(TINY, str(tmp_path), seed=11)
    ref = _forward_logits(TINY, load_params(TINY, str(tmp_path)),
                          list(range(5, 25)))
    got = _forward_logits(TINY, load_params(TINY, str(tmp_path), quantize="int8"),
                          list(range(5, 25)))
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 0.05, f"relative logits error {rel:.4f}"


def test_gguf_quantized_load(tmp_path):
    from dynamo_tpu.gguf import GGUFReader, load_params_from_gguf, write_gguf

    cfg = ModelConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64,
    )
    rng = np.random.default_rng(1)
    D, H, Hk, Dh = (cfg.hidden_size, cfg.num_attention_heads,
                    cfg.num_key_value_heads, cfg.head_dim)
    F, V, L = cfg.intermediate_size, cfg.vocab_size, cfg.num_hidden_layers

    def t(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    tensors = {
        "token_embd.weight": t(V, D),
        "output_norm.weight": np.ones((D,), np.float32),
        # no output.weight: tied-embeddings + quantized lm_head derivation
    }
    for i in range(L):
        tensors.update({
            f"blk.{i}.attn_norm.weight": np.ones((D,), np.float32),
            f"blk.{i}.attn_q.weight": t(H * Dh, D),
            f"blk.{i}.attn_k.weight": t(Hk * Dh, D),
            f"blk.{i}.attn_v.weight": t(Hk * Dh, D),
            f"blk.{i}.attn_output.weight": t(D, H * Dh),
            f"blk.{i}.ffn_norm.weight": np.ones((D,), np.float32),
            f"blk.{i}.ffn_gate.weight": t(F, D),
            f"blk.{i}.ffn_up.weight": t(F, D),
            f"blk.{i}.ffn_down.weight": t(D, F),
        })
    path = str(tmp_path / "m.gguf")
    write_gguf(path, {"general.architecture": "llama"}, tensors)
    with GGUFReader(path) as r:
        ref = load_params_from_gguf(cfg, r)
        qp = load_params_from_gguf(cfg, r, quantize="int8")
    assert qp["wq"].dtype.name == "int8"
    assert qp["lm_head"].dtype.name == "int8"  # tied, derived from embed
    deq = np.asarray(qp["wq"], np.float32)[0] * np.asarray(qp["wq_scale"])[0][None, :]
    np.testing.assert_allclose(
        deq, np.asarray(ref["wq"], np.float32)[0], atol=0.02, rtol=0.1
    )


async def _generate(engine, prompt_ids, max_tokens=8):
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        request_id="q", token_ids=prompt_ids,
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    toks = []
    adapter = engine.as_async_engine()
    async for out in adapter.generate(req, Context()):
        toks.extend(out.token_ids)
    return toks


async def test_engine_serves_int8():
    """The engine generates deterministically with quantization=int8 and
    the fused multi-step path."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine

    async def run():
        engine = await JaxEngine.launch(
            EngineConfig(
                model_path="", model_name="q8", random_weights=True,
                quantization="int8", num_blocks=64, block_size=8,
                max_batch_size=4, decode_steps=2, kv_cache_dtype="float32",
            ),
            model_config=TINY,
        )
        try:
            return await _generate(engine, list(range(1, 20)))
        finally:
            await engine.shutdown()

    t1 = await run()
    t2 = await run()
    assert len(t1) == 8 and t1 == t2
