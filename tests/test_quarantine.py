"""Step-failure containment: one poisoned request must not fail every
in-flight stream (VERDICT r2 weak #6). Submit-time validation catches
garbage before it reaches the jitted step; a failure in a prefill step
quarantines the prefilling requests and keeps decode streams alive."""

import asyncio
import os

import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


def _cfg(**kw):
    defaults = dict(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=128, block_size=8, max_batch_size=8,
        prefill_chunk_size=32, max_model_len=256, decode_steps=4,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _gen(engine, prompt, max_tokens=8, request_id="r"):
    req = PreprocessedRequest(
        request_id=request_id, token_ids=list(prompt),
        sampling=SamplingOptions(use_greedy=True),
        stop=StopConditions(max_tokens=max_tokens),
    )
    out, fin = [], None
    async for item in engine.as_async_engine().generate(req, Context()):
        out.extend(item.token_ids)
        if item.is_final:
            fin = item
    return out, fin


async def test_submit_rejects_garbage_token_ids():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_cfg())
    try:
        for bad in ([], [2**20], [-3], [1, 10**9]):
            with pytest.raises(ValueError):
                engine.submit(
                    PreprocessedRequest(
                        request_id="bad", token_ids=bad,
                        stop=StopConditions(max_tokens=4),
                    ),
                    Context(),
                )
        # engine still healthy
        toks, _ = await _gen(engine, range(1, 20), request_id="ok")
        assert len(toks) == 8
    finally:
        await engine.shutdown()


async def test_prefill_step_failure_quarantines_only_prefills():
    """Inject a PERSISTENT device-step failure while a straggler
    prefills mid-decode: after the free transient retry, the straggler
    gets an ERROR finish; the decode streams finish their full
    generation untouched."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_cfg())
    try:
        # poison: EVERY dispatch that carries prefill work raises while
        # armed (a transient single failure would be absorbed by the
        # retry — see test_transient_step_failure_retries below)
        orig_mixed = engine._dispatch_mixed
        orig_step = engine._run_device_step
        state = {"armed": False, "fired": 0}

        def boom_mixed(works, seqs, *a, **kw):
            if state["armed"]:
                state["fired"] += 1
                raise RuntimeError("injected prefill failure")
            return orig_mixed(works, seqs, *a, **kw)

        def boom_step(arrays, sampling, **kw):
            if (
                state["armed"]
                and arrays["tokens"].shape[1] > 1  # a prefill dispatch
            ):
                state["fired"] += 1
                raise RuntimeError("injected prefill failure")
            return orig_step(arrays, sampling, **kw)

        engine._dispatch_mixed = boom_mixed
        engine._run_device_step = boom_step

        async def victim():
            # arm only once every survivor is DECODING — a wall-clock
            # sleep here guessed at prefill latency and flaked whenever
            # a loaded machine prefilled slower than the guess
            # (engine.wait_for_state is the injectable replacement)
            await engine.wait_for_state(
                lambda e: e.scheduler is not None
                and e.scheduler.num_running >= 3
                and all(s.generated >= 1 for s in e.scheduler.running),
            )
            state["armed"] = True
            try:
                return await _gen(engine, range(1, 12), request_id="victim")
            finally:
                state["armed"] = False  # let retries of later work pass
        survivors = asyncio.gather(*[
            _gen(engine, range(1, 10 + i), max_tokens=30,
                 request_id=f"live{i}")
            for i in range(3)
        ])
        v_out, v_fin = await victim()
        results = await survivors
        assert state["fired"] >= 2, "injection never re-triggered"
        assert v_fin.finish_reason == FinishReason.ERROR
        assert v_out == []
        for toks, fin in results:
            assert len(toks) == 30, fin
            assert fin.finish_reason == FinishReason.LENGTH
        # engine accepts new work afterwards
        toks, _ = await _gen(engine, range(1, 16), request_id="after")
        assert len(toks) == 8
    finally:
        await engine.shutdown()


async def test_repeated_failures_fall_back_to_fail_all():
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_cfg())
    try:
        def always_boom(*a, **kw):
            raise RuntimeError("persistent failure")

        engine._run_device_step = always_boom
        engine._dispatch_mixed = always_boom
        engine._dispatch_multi_step = always_boom
        outs = await asyncio.gather(*[
            _gen(engine, range(1, 10), request_id=f"r{i}") for i in range(3)
        ])
        for toks, fin in outs:
            assert fin.finish_reason == FinishReason.ERROR
    finally:
        await engine.shutdown()


async def test_transient_step_failure_retries():
    """A ONE-SHOT step failure (device hiccup) is retried, not charged
    to the in-flight requests: everyone finishes normally (ADVICE r3:
    don't terminate innocent requests on transient faults)."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(_cfg())
    try:
        orig_step = engine._run_device_step
        orig_mixed = engine._dispatch_mixed
        orig_multi = engine._dispatch_multi_step
        state = {"fired": False}

        def boom_once(orig):
            def wrapper(*a, **kw):
                if not state["fired"]:
                    state["fired"] = True
                    raise RuntimeError("transient device fault")
                return orig(*a, **kw)
            return wrapper

        engine._run_device_step = boom_once(orig_step)
        engine._dispatch_mixed = boom_once(orig_mixed)
        engine._dispatch_multi_step = boom_once(orig_multi)
        toks, fin = await _gen(engine, range(1, 20), request_id="tr")
        assert state["fired"]
        assert fin.finish_reason == FinishReason.LENGTH
        assert len(toks) == 8
    finally:
        await engine.shutdown()
