"""Ring / Ulysses sequence-parallel attention vs single-device oracle.

Runs on the virtual 8-device CPU mesh (conftest) — the multi-chip rung of
the test ladder.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.parallel.ring_attention import (
    reference_causal_attention,
    ring_attention,
    ulysses_attention,
)


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def _data(B, T, H, Hk, Dh, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, T, H, Dh)).astype(np.float32)
    k = rng.standard_normal((B, T, Hk, Dh)).astype(np.float32)
    v = rng.standard_normal((B, T, Hk, Dh)).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("H,Hk", [(8, 8), (8, 2), (8, 1)])
def test_ring_attention_matches_reference(H, Hk):
    mesh = _mesh()
    B, T, Dh = 2, 64, 16  # T=64 over 8 shards -> 8 tokens per device
    q, k, v = _data(B, T, H, Hk, Dh)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh)
    ref = reference_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_jit_under_mesh():
    mesh = _mesh()
    B, T, H, Hk, Dh = 1, 32, 4, 2, 8
    q, k, v = _data(B, T, H, Hk, Dh, seed=3)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    f = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh))
    out = f(qs, ks, vs)
    ref = reference_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)
    # output keeps the sequence sharding (no gather to one device)
    assert out.sharding.spec == P(None, "sp", None, None)


@pytest.mark.parametrize("H,Hk", [(8, 8), (16, 8)])
def test_ulysses_matches_reference(H, Hk):
    mesh = _mesh()
    B, T, Dh = 2, 64, 16
    q, k, v = _data(B, T, H, Hk, Dh, seed=1)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, mesh)
    ref = reference_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ulysses_rejects_indivisible_heads():
    mesh = _mesh()
    q, k, v = _data(1, 16, 4, 2, 8)
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention(q, k, v, mesh)


def test_ring_attention_long_context_bf16():
    """Longer sequence in bf16 — the intended long-context prefill dtype."""
    mesh = _mesh()
    B, T, H, Hk, Dh = 1, 256, 4, 2, 32
    q, k, v = _data(B, T, H, Hk, Dh, seed=7)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sh) for x in (qb, kb, vb))
    out = ring_attention(qs, ks, vs, mesh)
    ref = reference_causal_attention(qb, kb, vb)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32),
        np.asarray(ref, dtype=np.float32),
        rtol=5e-2, atol=5e-2,
    )
