"""telemetry/roofline.py: the ONE roofline formula bench.py and the
attribution ledger share, pinned to the 8B int8 numbers documented in
docs/performance.md (the byte table and the ~5.4k → ~5.9k tok/s
bf16→int8 KV headline move)."""

import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.telemetry.roofline import (
    HBM_BW_BYTES,
    RooflineModel,
    build_roofline,
    kv_bytes_per_token,
    param_bytes,
    phase_ideal_bytes,
    roofline_tok_s,
    step_bytes,
)


def _mc_8b() -> ModelConfig:
    # DeepSeek-R1-Distill-Llama-8B geometry (BASELINE.md config 1) —
    # the bench.py headline shape
    return ModelConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32,
        num_key_value_heads=8, max_position_embeddings=8192,
    )


# headline workload: batch 64, isl 128 / osl 128 -> avg ctx 192
B, AVG_CTX = 64, 192


def test_8b_int8_param_bytes_pin():
    # int8 weights ≈ 8.03 GB (fits a 16 GB v5e chip with KV headroom;
    # docs/performance.md: MLP+projections ~6.98 GB + 2·V·D ~1.05 GB)
    assert param_bytes(_mc_8b(), "int8") == pytest.approx(8.03e9, rel=0.01)
    assert param_bytes(_mc_8b(), None) == 2 * param_bytes(_mc_8b(), "int8")


def test_8b_kv_bytes_per_token_pin():
    mc = _mc_8b()
    # 2·L·Hk·Dh = 65536 elements/token; int8 pays +4/128 for the
    # per-(slot, head) f32 scale, fp8 is scale-free
    assert kv_bytes_per_token(mc, "bfloat16") == 131072.0
    assert kv_bytes_per_token(mc, "int8") == 65536 * (1 + 4 / 128)
    assert kv_bytes_per_token(mc, "float8_e4m3fn") == 65536.0


def test_8b_headline_roofline_pins():
    mc = _mc_8b()
    # the numbers every BENCH_r* vs_baseline was computed against:
    # bf16 KV -> ~5437 tok/s (ROADMAP item 2's denominator), int8 KV ->
    # ~5916 (docs/performance.md "the target moves from ~5.4k to ~5.9k")
    assert roofline_tok_s(mc, B, AVG_CTX, "int8", "bfloat16") == pytest.approx(
        5437.0, abs=1.0
    )
    assert roofline_tok_s(mc, B, AVG_CTX, "int8", "int8") == pytest.approx(
        5915.7, abs=1.0
    )


def test_8b_phase_byte_table_pins():
    # the docs/performance.md byte table at the headline config
    ph = phase_ideal_bytes(_mc_8b(), B, AVG_CTX, "int8", "int8")
    assert ph["mlp"] == pytest.approx(6.98e9, rel=0.01)
    assert ph["attention"] == pytest.approx(0.83e9, rel=0.01)
    assert ph["lm_head"] == pytest.approx(0.526e9, rel=0.01)
    assert ph["sampling"] == pytest.approx(33e6, rel=0.01)
    bf16 = phase_ideal_bytes(_mc_8b(), B, AVG_CTX, "int8", "bfloat16")
    assert bf16["attention"] == pytest.approx(1.61e9, rel=0.01)
    # phases + embedding = the step total (phase table excludes the
    # embedding read, which rides param_bytes)
    mc = _mc_8b()
    assert (
        ph["mlp"] + ph["lm_head"] + ph["attention"]
        <= step_bytes(mc, B, AVG_CTX, "int8", "int8")
    )


def test_bench_imports_the_same_formulas():
    """bench.py must not grow a private copy again: its helpers ARE the
    shared ones."""
    import bench

    mc = _mc_8b()
    assert bench._param_bytes(mc, "int8") == param_bytes(mc, "int8")
    assert bench._kv_bytes_per_token(mc, "int8") == kv_bytes_per_token(
        mc, "int8"
    )
    assert bench.HBM_BW_BYTES == HBM_BW_BYTES


def test_roofline_model_matches_free_functions():
    mc = _mc_8b()
    rm = build_roofline(mc, "int8", "int8")
    assert isinstance(rm, RooflineModel)
    # ideal_step_s at the headline geometry reproduces the tok/s pin
    # (the model adds the [B, V] sampling read — sub-0.5% at 8B)
    ideal = rm.ideal_step_s(B, B * AVG_CTX)
    assert B / ideal == pytest.approx(
        roofline_tok_s(mc, B, AVG_CTX, "int8", "int8"), rel=0.005
    )
    fr = rm.phase_fractions(B, B * AVG_CTX)
    assert sum(fr.values()) == pytest.approx(1.0)
    # weight-bound decode: MLP dominates the prior
    assert fr["mlp"] > 0.5 and fr["attention"] < 0.2


def test_roofline_model_phase_prior_matches_phase_table():
    """The ledger's device-split prior and bench --phases must
    decompose against the IDENTICAL byte table (the embedding gather
    belongs to neither: it reads B rows, not the table)."""
    mc = _mc_8b()
    rm = build_roofline(mc, "int8", "int8")
    ph = phase_ideal_bytes(mc, B, AVG_CTX, "int8", "int8")
    total = sum(ph.values())
    fr = rm.phase_fractions(B, B * AVG_CTX)
    for k, v in ph.items():
        assert fr[k] == pytest.approx(v / total, rel=1e-9), k
