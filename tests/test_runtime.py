"""Distributed runtime tests (≈ reference lib/runtime/tests/{pipeline,lifecycle}.rs).

Two deployment shapes are exercised:
- static: one process, in-memory store
- distributed: coordinator on TCP + two DistributedRuntimes ("processes")
  in one event loop, talking over real sockets.
"""

import asyncio
from typing import Any, AsyncIterator

import pytest

from dynamo_tpu.runtime.component import Instance
from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.engine import AsyncEngine, Context, FnEngine, collect
from dynamo_tpu.runtime.pipeline import Operator, build_pipeline
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.store.memory import MemoryStore
from dynamo_tpu.store.server import StoreServer


async def echo_stream(request: Any, ctx: Context) -> AsyncIterator[Any]:
    """Stream each token of the request back (≈ reference EchoEngineCore)."""
    for tok in request["tokens"]:
        if ctx.is_stopped:
            return
        yield {"token": tok}


def make_static_config() -> RuntimeConfig:
    return RuntimeConfig(static=True, worker_host="127.0.0.1", lease_ttl_s=2.0,
                         lease_keepalive_s=0.5)


async def test_static_serve_and_call():
    drt = await DistributedRuntime.create(config=make_static_config())
    try:
        ep = drt.namespace("test").component("echo").endpoint("generate")
        await ep.serve(FnEngine(echo_stream))
        client = await ep.client()
        ids = await client.wait_for_instances(timeout_s=5)
        assert len(ids) == 1
        stream = await client.generate_direct(ids[0], {"tokens": [1, 2, 3]})
        items = [i async for i in stream]
        assert items == [{"token": 1}, {"token": 2}, {"token": 3}]
        await client.close()
    finally:
        await drt.shutdown()


async def test_push_router_round_robin_and_failover():
    """Two workers; round-robin spreads load; killing one fails over."""
    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_port=server.port, worker_host="127.0.0.1",
        lease_ttl_s=1.0, lease_keepalive_s=0.2,
    )
    w1 = await DistributedRuntime.create(config=cfg())
    w2 = await DistributedRuntime.create(config=cfg())
    frontend = await DistributedRuntime.create(config=cfg())

    async def worker_engine(tag: str):
        async def gen(request: Any, ctx: Context) -> AsyncIterator[Any]:
            yield {"worker": tag, "echo": request}

        return FnEngine(gen)

    try:
        for drt, tag in ((w1, "w1"), (w2, "w2")):
            ep = drt.namespace("ns").component("gen").endpoint("generate")
            await ep.serve(await worker_engine(tag))

        ep = frontend.namespace("ns").component("gen").endpoint("generate")
        client = await ep.client()
        await client.wait_for_instances(timeout_s=5)
        # wait until both instances are discovered
        for _ in range(50):
            if len(client.instance_ids()) == 2:
                break
            await asyncio.sleep(0.05)
        assert len(client.instance_ids()) == 2

        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        seen = set()
        for i in range(4):
            items = await collect(router.generate({"n": i}, Context()))
            seen.add(items[0]["worker"])
        assert seen == {"w1", "w2"}

        # kill w1: lease revoked => discovery prunes it; router fails over
        await w1.shutdown()
        for _ in range(100):
            if len(client.instance_ids()) == 1:
                break
            await asyncio.sleep(0.05)
        assert len(client.instance_ids()) == 1
        for i in range(3):
            items = await collect(router.generate({"n": i}, Context()))
            assert items[0]["worker"] == "w2"
        await client.close()
    finally:
        for drt in (w2, frontend):
            await drt.shutdown()
        await server.stop()


async def test_cancellation_stops_worker_stream():
    """Client-side kill propagates to the worker's Context."""
    drt = await DistributedRuntime.create(config=make_static_config())
    try:
        produced = []

        async def slow(request: Any, ctx: Context) -> AsyncIterator[Any]:
            for i in range(1000):
                if ctx.is_stopped:
                    return
                produced.append(i)
                yield {"i": i}
                await asyncio.sleep(0.01)

        ep = drt.namespace("ns").component("slow").endpoint("generate")
        await ep.serve(FnEngine(slow))
        client = await ep.client()
        (iid,) = await client.wait_for_instances(5)
        ctx = Context()
        stream = await client.generate_direct(iid, {}, ctx)
        got = []
        async for item in stream:
            got.append(item)
            if len(got) == 3:
                ctx.kill()
                break
        await asyncio.sleep(0.3)
        n = len(produced)
        await asyncio.sleep(0.3)
        assert len(produced) == n, "worker kept producing after kill"
        assert n < 1000
        await client.close()
    finally:
        await drt.shutdown()


async def test_pipeline_operators():
    """Forward/backward edges compose (≈ reference pipeline.rs tests)."""

    class TokenizeOp(Operator):
        async def forward(self, request: str, context: Context):
            return {"tokens": [ord(c) for c in request]}, {"n": len(request)}

        async def backward(self, stream, state, context):
            async for item in stream:
                yield chr(item["token"] + 1)

    engine = build_pipeline(TokenizeOp(), FnEngine(echo_stream))
    out = await collect(engine.generate("abc", Context()))
    assert out == ["b", "c", "d"]


async def test_pipeline_type_errors():
    with pytest.raises(TypeError):
        build_pipeline(FnEngine(echo_stream), FnEngine(echo_stream))
    with pytest.raises(ValueError):
        build_pipeline()


async def test_component_events_pubsub():
    drt = await DistributedRuntime.create(config=make_static_config())
    try:
        comp = drt.namespace("ns").component("worker")
        sub = await comp.subscribe("kv_events")
        await comp.publish("kv_events", {"block_hash": 42, "op": "stored"})
        it = sub.__aiter__()
        subject, payload = await asyncio.wait_for(it.__anext__(), 5)
        assert subject == "ns.worker.kv_events"
        assert payload == {"block_hash": 42, "op": "stored"}
        await sub.close()
    finally:
        await drt.shutdown()


async def test_static_client_without_discovery():
    """Static mode: direct instance without store watch
    (≈ reference static client, component.rs:294-300)."""
    drt = await DistributedRuntime.create(config=make_static_config())
    try:
        ep = drt.namespace("ns").component("echo").endpoint("generate")
        inst = await ep.serve(FnEngine(echo_stream))
        static = Instance(
            instance_id=inst.instance_id, host="127.0.0.1", port=inst.port,
            namespace="ns", component="echo", endpoint="generate",
        )
        client = await ep.client(static_instance=static)
        stream = await client.generate_direct(inst.instance_id, {"tokens": [9]})
        assert [i async for i in stream] == [{"token": 9}]
        await client.close()
    finally:
        await drt.shutdown()
