"""Sampling semantics: min_p / logit_bias / penalty parity vs a numpy
reference, including exactness inside fused K-step decode windows.

The reference carries these options into its engines
(reference: lib/llm/src/protocols/common.rs:263-309); a request must get
the behavior it asked for — silent drops are a correctness bug.
"""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.engine.sampling import (
    SamplingBatch,
    reference_sample_numpy,
    sample,
)
from dynamo_tpu.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


def _device_sample(logits: np.ndarray, batch: SamplingBatch):
    import jax

    toks, lps = jax.jit(sample)(logits.astype(np.float32), batch.arrays)
    return np.asarray(toks), np.asarray(lps)


def test_greedy_with_logit_bias():
    rng = np.random.default_rng(0)
    V = 64
    logits = rng.normal(size=(2, V)).astype(np.float32)
    # bias strong enough to force token 7 on row 0; row 1 unbiased
    opts = [
        SamplingOptions(use_greedy=True, logit_bias={7: 100.0}),
        SamplingOptions(use_greedy=True),
    ]
    batch = SamplingBatch.from_options(opts, [1, 2])
    toks, _ = _device_sample(logits, batch)
    assert toks[0] == 7
    assert toks[1] == int(np.argmax(logits[1]))


def test_min_p_filters_unlikely_tokens():
    # three tokens: two near-equal, one 20 logits below. min_p=0.5 keeps
    # only tokens with prob >= 0.5*max -> token 2 must never be sampled.
    logits = np.full((1, 3), -1e9, np.float32)
    logits[0, :3] = [0.0, -0.1, -20.0]
    opts = [SamplingOptions(temperature=1.0, min_p=0.5)]
    seen = set()
    for seed in range(64):
        batch = SamplingBatch.from_options(opts, [seed])
        toks, _ = _device_sample(logits, batch)
        seen.add(int(toks[0]))
    assert 2 not in seen
    assert seen == {0, 1}  # both survivors actually get sampled


def test_penalties_match_numpy_reference():
    rng = np.random.default_rng(1)
    B, V = 4, 128
    logits = rng.normal(size=(B, V)).astype(np.float32) * 3
    opts = [
        SamplingOptions(
            use_greedy=True, frequency_penalty=0.8, presence_penalty=0.3
        ),
        SamplingOptions(use_greedy=True, repetition_penalty=1.7),
        SamplingOptions(
            use_greedy=True,
            frequency_penalty=1.1,
            presence_penalty=-0.4,
            repetition_penalty=1.3,
            logit_bias={3: 2.5, 9: -1.0},
        ),
        SamplingOptions(use_greedy=True),  # control row: no penalties
    ]
    gen_counts = [{5: 3, 17: 1}, {40: 2}, {3: 4, 9: 1, 77: 2}, {}]
    prompt_ids = [
        np.array([1, 2, 3], np.int32),
        np.array([40, 41], np.int32),
        np.array([9], np.int32),
        np.zeros((0,), np.int32),
    ]
    batch = SamplingBatch.from_options(opts, [0, 0, 0, 0], gen_counts, prompt_ids)
    assert batch.has_penalties
    toks, lps = _device_sample(logits, batch)
    for row in range(B):
        ref = reference_sample_numpy(logits[row], batch.arrays, row)
        assert toks[row] == int(np.argmax(ref)), f"row {row}"
    # control row unaffected by other rows' penalties
    assert toks[3] == int(np.argmax(logits[3]))


def test_repetition_penalty_breaks_greedy_loop():
    # a fixed logit landscape would greedily emit token 5 forever;
    # repetition penalty must steer away once 5 has been generated
    V = 32
    logits = np.zeros((1, V), np.float32)
    logits[0, 5] = 2.0
    logits[0, 6] = 1.5
    opts = [SamplingOptions(use_greedy=True, repetition_penalty=2.0)]
    batch = SamplingBatch.from_options(
        opts, [0], [{5: 1}], [np.zeros((0,), np.int32)]
    )
    toks, _ = _device_sample(logits, batch)
    assert toks[0] == 6  # 2.0/2.0 = 1.0 < 1.5


# ---------------------------------------------------------------------------
# Engine-level: penalties inside fused decode windows
# ---------------------------------------------------------------------------


async def _run_engine(prompt, sampling, decode_steps, max_tokens=10):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.runtime.engine import Context

    engine = await JaxEngine.launch(
        EngineConfig(
            model_path=MODEL_DIR, model_name="tiny", random_weights=True,
            num_blocks=128, block_size=8, max_batch_size=8,
            prefill_chunk_size=32, max_model_len=256,
            decode_steps=decode_steps,
        )
    )
    try:
        adapter = engine.as_async_engine()
        req = PreprocessedRequest(
            request_id="pen",
            token_ids=list(prompt),
            sampling=sampling,
            stop=StopConditions(max_tokens=max_tokens),
        )
        out = []
        async for item in adapter.generate(req, Context()):
            out.extend(item.token_ids)
        return out
    finally:
        await engine.shutdown()


async def test_penalties_exact_inside_fused_windows():
    """decode_steps=4 with penalties must be token-identical to
    decode_steps=1 (the dense count table carried through the window
    scan matches per-step host updates), and must differ from the
    penalty-free greedy run (the penalties actually do something)."""
    prompt = list(range(1, 20))
    pen = SamplingOptions(
        use_greedy=True, repetition_penalty=1.8, frequency_penalty=0.7,
        presence_penalty=0.4,
    )
    plain = SamplingOptions(use_greedy=True)
    single = await _run_engine(prompt, pen, decode_steps=1)
    fused = await _run_engine(prompt, pen, decode_steps=4)
    assert single == fused
    unpenalized = await _run_engine(prompt, plain, decode_steps=1)
    assert single != unpenalized


def test_openai_logit_bias_plumbing():
    from dynamo_tpu.protocols.openai import ChatCompletionRequest, ChatMessage

    req = ChatCompletionRequest(
        model="m",
        messages=[ChatMessage(role="user", content="hi")],
        logit_bias={"5": 3.0, "17": -2.0},
        frequency_penalty=0.5,
    )
    so = req.sampling_options()
    assert so.logit_bias == {5: 3.0, 17: -2.0}
    assert so.frequency_penalty == 0.5
    assert so.needs_penalties
    assert not SamplingOptions(logit_bias={1: 1.0}).needs_penalties
