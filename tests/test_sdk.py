"""SDK decorators, component runner, supervisor, planner, metrics service.

Reference test analogue: deploy/sdk/src/dynamo/sdk/tests/test_e2e.py —
a full `dynamo serve` of a small pipeline with real coordinator +
subprocesses, asserting responses and scaling behavior.
"""

import asyncio
import json
import os
import re
import sys

import pytest

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.sdk.allocator import AllocationError, TpuAllocator
from dynamo_tpu.sdk.service import DynamoService, depends, endpoint, service
from dynamo_tpu.store.memory import MemoryStore
from dynamo_tpu.store.server import StoreServer


# --- a tiny two-component graph used across tests -------------------------


@service(dynamo={"namespace": "sdktest"})
class Backend:
    @endpoint()
    async def generate(self, request):
        for t in request["tokens"]:
            yield {"token": t * 2}


@service(dynamo={"namespace": "sdktest"}, replicas=1)
class Middle:
    backend = depends(Backend)

    @endpoint()
    async def generate(self, request):
        async for item in self.backend.generate(request):
            yield {"token": item["token"] + 1}


def test_decorators_and_graph():
    assert isinstance(Backend, DynamoService)
    assert Backend.endpoints == {"generate": "generate"}
    assert Middle.dependencies == {"backend": Backend}
    names = [s.name for s in Middle.graph()]
    assert names == ["Backend", "Middle"]  # dependencies first
    merged = Middle.config.merged({"replicas": 3, "resources": {"tpu": 2}})
    assert merged.replicas == 3 and merged.resources == {"tpu": 2}


def test_allocator():
    alloc = TpuAllocator(total_chips=4)
    a = alloc.allocate("w1", {"tpu": 2})
    assert a.chip_ids == [0, 1]
    assert "TPU_VISIBLE_DEVICES" in a.env()
    b = alloc.allocate("cp", {})
    assert b.env() == {"DYN_JAX_PLATFORM": "cpu"}
    with pytest.raises(AllocationError):
        alloc.allocate("w2", {"tpu": 3})
    alloc.release("w1")
    assert alloc.free_chips == 4


async def test_serve_service_and_dependency_calls():
    """Two components served in-process; depends() edge streams through
    the real endpoint plane."""
    from dynamo_tpu.sdk.runner import serve_service

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_port=server.port, worker_host="127.0.0.1",
        lease_ttl_s=2.0, lease_keepalive_s=0.5,
    )
    drt_b = await DistributedRuntime.create(config=cfg())
    drt_m = await DistributedRuntime.create(config=cfg())
    try:
        await serve_service(Backend, drt_b)
        mid = await serve_service(Middle, drt_m)
        out = []
        async for item in mid.backend.generate({"tokens": [1, 2, 3]}):
            out.append(item["token"])
        assert out == [2, 4, 6]
        # and through Middle's own endpoint engine
        comp = drt_b.namespace("sdktest").component("middle")
        client = await comp.endpoint("generate").client()
        # generous budget: the wait is event-driven (store watch), but
        # under full-suite load discovery propagation can take far
        # longer than the happy-path seconds (r3 flake)
        ids = await client.wait_for_instances(timeout_s=60)
        stream = await client.generate_direct(ids[0], {"tokens": [5]})
        items = [i async for i in stream]
        assert items == [{"token": 11}]
        await client.close()
    finally:
        await drt_m.shutdown()
        await drt_b.shutdown()
        await server.stop()


# --- supervisor e2e (real subprocesses) -----------------------------------

GRAPH_MODULE = "tests.sdk_graph"


async def test_supervisor_graph_and_scaling(tmp_path, monkeypatch):
    from dynamo_tpu.planner.connector import LocalConnector
    from dynamo_tpu.sdk.runner import load_service
    from dynamo_tpu.sdk.serving import Supervisor, state_file

    monkeypatch.setenv("DYN_LOCAL_STATE_DIR", str(tmp_path))
    monkeypatch.setenv("DYN_JAX_PLATFORM", "cpu")
    monkeypatch.setenv("PYTHONPATH", os.path.dirname(os.path.dirname(__file__)))
    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    from dynamo_tpu.store.client import StoreClient

    store = await StoreClient.connect("127.0.0.1", server.port)
    entry = load_service(f"{GRAPH_MODULE}:Frontend")
    import importlib

    mod = importlib.import_module(GRAPH_MODULE)
    specs = {
        obj.name: f"{GRAPH_MODULE}:{attr}"
        for attr, obj in vars(mod).items()
        if isinstance(obj, DynamoService)
    }
    sup = Supervisor(
        entry=entry, store=store, namespace="supns",
        store_host="127.0.0.1", store_port=server.port,
        service_specs=specs,
    )
    await sup.start()
    try:
        drt = await DistributedRuntime.create(
            config=RuntimeConfig(store_port=server.port, worker_host="127.0.0.1")
        )
        comp = drt.namespace("supns").component("frontend")
        client = await comp.endpoint("generate").client()
        ids = await client.wait_for_instances(timeout_s=120)
        stream = await client.generate_direct(ids[0], {"tokens": [3]})
        items = [i async for i in stream]
        assert items == [{"token": 7}]  # 3*2 (worker) then +1 (frontend)

        # planner connector scales the worker component up then down
        conn = LocalConnector(store, "supns", timeout_s=60)
        assert await conn.add_component("Worker")
        assert await conn.replicas("Worker") == 2
        assert await conn.remove_component("Worker")
        assert await conn.replicas("Worker") == 1
        assert os.path.exists(state_file("supns"))
        with open(state_file("supns")) as f:
            st = json.load(f)
        assert st["components"]["Worker"]["replicas"] == 1
        await client.close()
        await drt.shutdown()
    finally:
        await sup.shutdown()
        await store.close()
        await server.stop()


# --- planner unit logic ----------------------------------------------------


class FakeConnector:
    def __init__(self):
        self.calls = []

    async def add_component(self, c):
        self.calls.append(("add", c))
        return True

    async def remove_component(self, c):
        self.calls.append(("remove", c))
        return True


async def test_planner_thresholds_and_grace():
    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.planner.planner import Planner, PlannerConfig

    store = MemoryStore()
    server = StoreServer(store, port=0)
    await server.start()
    drt = await DistributedRuntime.create(
        config=RuntimeConfig(store_port=server.port, worker_host="127.0.0.1")
    )
    comp = drt.namespace("plns").component("backend")
    conn = FakeConnector()
    planner = Planner(
        drt.store, comp, conn,
        config=PlannerConfig(grace_cycles=2, max_decode=4, min_decode=1),
        decode_workers=1,
    )
    # feed synthetic overloaded metrics directly into the aggregator
    planner.aggregator.update(
        ForwardPassMetrics(worker_id=1, gpu_cache_usage_perc=0.95)
    )
    snap = await planner.collect()
    await planner.make_adjustments(snap)  # streak 1: no action (grace)
    assert conn.calls == []
    await planner.make_adjustments(snap)  # streak 2: scale up
    assert conn.calls == [("add", "backend")]
    assert planner.decode_workers == 2
    # low load scales back down after grace
    planner.aggregator.update(
        ForwardPassMetrics(worker_id=1, gpu_cache_usage_perc=0.1)
    )
    snap = await planner.collect()
    await planner.make_adjustments(snap)
    await planner.make_adjustments(snap)
    assert conn.calls[-1] == ("remove", "backend")
    assert planner.decode_workers == 1
    await planner.close()
    await drt.shutdown()
    await server.stop()


# --- metrics service --------------------------------------------------------


async def test_metrics_service_render_and_http():
    import aiohttp

    from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.metrics.service import MetricsService

    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    drt = await DistributedRuntime.create(
        config=RuntimeConfig(store_port=server.port, worker_host="127.0.0.1")
    )
    comp = drt.namespace("mns").component("backend")
    svc = MetricsService(comp, host="127.0.0.1", port=0)
    await svc.start()
    try:
        svc.aggregator.update(
            ForwardPassMetrics(
                worker_id=0xAB, gpu_cache_usage_perc=0.5,
                kv_active_blocks=10, kv_total_blocks=100,
                request_active_slots=2, request_total_slots=8,
            )
        )
        await comp.namespace.publish(
            "kv-hit-rate", {"worker_id": 0xAB, "isl_blocks": 10, "overlap_blocks": 5}
        )
        # bounded wait for the hit-rate pump (one fixed sleep flaked
        # under full-suite load)
        for _ in range(50):
            if svc._hit_events:
                break
            await asyncio.sleep(0.05)
        text = svc.render()
        assert "llm_kv_load_avg 0.5" in text
        # integer-valued samples may render as "10" or "10.0"
        assert re.search(r"^llm_kv_blocks_active 10(\.0)?$", text, re.M)
        assert 'llm_worker_kv_cache_usage{worker="ab"} 0.5' in text
        assert "llm_kv_avg_hit_rate 0.5" in text
        async with aiohttp.ClientSession() as sess:
            async with sess.get(f"http://127.0.0.1:{svc.port}/metrics") as resp:
                assert resp.status == 200
                body = await resp.text()
                assert "llm_workers_reporting" in body
    finally:
        await svc.close()
        await drt.shutdown()
        await server.stop()


def test_planner_metrics_logger(tmp_path):
    """JSONL always written; TensorBoard events when torch is present
    (reference: planner tensorboard logging)."""
    import json as _json

    from dynamo_tpu.planner.metrics_log import MetricsLogger

    mlog = MetricsLogger(str(tmp_path), tensorboard=True)
    mlog({"kv_load_mean": 0.5, "prefill_queue_depth": 2.0, "ts": 1.0})
    mlog({"kv_load_mean": 0.7, "prefill_queue_depth": 0.0, "ts": 2.0})
    mlog.close()
    lines = [
        _json.loads(x)
        for x in open(tmp_path / "planner_metrics.jsonl")
    ]
    assert [r["kv_load_mean"] for r in lines] == [0.5, 0.7]
    import glob as _glob

    try:
        import torch  # noqa: F401
    except ImportError:
        return  # JSONL-only degradation is the designed behavior
    assert _glob.glob(str(tmp_path / "events.out.tfevents.*"))
