"""Shard-aware checkpoint loading (VERDICT r3 item 6, the 70B ladder):
``load_params_sharded`` must produce arrays identical to the stacked
loader — same global values, same shardings — while each process only
ever reads its own slices (safetensors partial reads +
jax.make_array_from_callback)."""

import json
import os

import jax
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import param_specs
from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh
from tests.test_quantization import _write_tiny_checkpoint


def _cfg(**kw):
    defaults = dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=128,
    )
    defaults.update(kw)
    return ModelConfig(**defaults)


def _host(arr) -> np.ndarray:
    return np.asarray(jax.device_get(arr))


@pytest.mark.parametrize("tied", [False, True])
@pytest.mark.parametrize("quantize", [None, "int8"])
def test_sharded_load_matches_stacked(tmp_path, tied, quantize):
    from dynamo_tpu.models.loader import load_params, load_params_sharded

    cfg = _cfg()
    path = str(tmp_path / "ckpt")
    _write_tiny_checkpoint(cfg, path, tied=tied, seed=3)
    # tp=8 exercises the 70B ladder's per-shard geometry (Hkv/tp = 1)
    mesh = build_mesh(MeshConfig(tp=8), jax.devices()[:8])
    ref = load_params(cfg, path, mesh, quantize=quantize)
    got = load_params_sharded(cfg, path, mesh, quantize=quantize)
    assert set(ref) == set(got)
    for name in sorted(ref):
        r, g = _host(ref[name]), _host(got[name])
        assert r.shape == g.shape, name
        assert r.dtype == g.dtype, name
        np.testing.assert_array_equal(r, g, err_msg=name)
        assert ref[name].sharding == got[name].sharding, name


def test_sharded_load_serves_through_engine(tmp_path):
    """resolve_model with DYN_SHARDED_LOAD=1 produces a servable model
    (forward parity is transitively covered by the equality test; this
    guards the resolve_model wiring)."""
    from dynamo_tpu.models import loader

    cfg = _cfg()
    path = str(tmp_path / "ckpt")
    _write_tiny_checkpoint(cfg, path, seed=7)
    mesh = build_mesh(MeshConfig(tp=8), jax.devices()[:8])
    os.environ["DYN_SHARDED_LOAD"] = "1"
    try:
        mc, params = loader.resolve_model(path, mesh=mesh)
    finally:
        os.environ.pop("DYN_SHARDED_LOAD", None)
    assert mc.hidden_size == cfg.hidden_size
    assert params["wq"].sharding.spec == param_specs(mc)["wq"]
