"""Shard-aware checkpoint loading (VERDICT r3 item 6, the 70B ladder):
``load_params_sharded`` must produce arrays identical to the stacked
loader — same global values, same shardings — while each process only
ever reads its own slices (safetensors partial reads +
jax.make_array_from_callback)."""

import json
import os

import jax
import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.llama import param_specs
from dynamo_tpu.parallel.mesh import MeshConfig, build_mesh
from tests.test_quantization import _write_tiny_checkpoint


def _cfg(**kw):
    defaults = dict(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=8,
        max_position_embeddings=128,
    )
    defaults.update(kw)
    return ModelConfig(**defaults)


def _host(arr) -> np.ndarray:
    return np.asarray(jax.device_get(arr))


@pytest.mark.parametrize("tied", [False, True])
@pytest.mark.parametrize("quantize", [None, "int8"])
def test_sharded_load_matches_stacked(tmp_path, tied, quantize):
    from dynamo_tpu.models.loader import load_params, load_params_sharded

    cfg = _cfg()
    path = str(tmp_path / "ckpt")
    _write_tiny_checkpoint(cfg, path, tied=tied, seed=3)
    # tp=8 exercises the 70B ladder's per-shard geometry (Hkv/tp = 1)
    mesh = build_mesh(MeshConfig(tp=8), jax.devices()[:8])
    ref = load_params(cfg, path, mesh, quantize=quantize)
    got = load_params_sharded(cfg, path, mesh, quantize=quantize)
    assert set(ref) == set(got)
    for name in sorted(ref):
        r, g = _host(ref[name]), _host(got[name])
        assert r.shape == g.shape, name
        assert r.dtype == g.dtype, name
        np.testing.assert_array_equal(r, g, err_msg=name)
        assert ref[name].sharding == got[name].sharding, name


def test_sharded_load_serves_through_engine(tmp_path):
    """resolve_model with DYN_SHARDED_LOAD=1 produces a servable model
    (forward parity is transitively covered by the equality test; this
    guards the resolve_model wiring)."""
    from dynamo_tpu.models import loader

    cfg = _cfg()
    path = str(tmp_path / "ckpt")
    _write_tiny_checkpoint(cfg, path, seed=7)
    mesh = build_mesh(MeshConfig(tp=8), jax.devices()[:8])
    os.environ["DYN_SHARDED_LOAD"] = "1"
    try:
        mc, params = loader.resolve_model(path, mesh=mesh)
    finally:
        os.environ.pop("DYN_SHARDED_LOAD", None)
    assert mc.hidden_size == cfg.hidden_size
    assert params["wq"].sharding.spec == param_specs(mc)["wq"]


def _write_moe_checkpoint(cfg, path, seed=0):
    """Mixtral-layout safetensors checkpoint (per-expert w1/w2/w3)."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)
    D, F, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    H, Hk, Dh, L = (cfg.num_attention_heads, cfg.num_key_value_heads,
                    cfg.head_dim, cfg.num_hidden_layers)
    E = cfg.num_local_experts
    t = {}
    t["model.embed_tokens.weight"] = rng.standard_normal((V, D)).astype(np.float32)
    t["model.norm.weight"] = np.ones((D,), np.float32)
    t["lm_head.weight"] = rng.standard_normal((V, D)).astype(np.float32)
    for i in range(L):
        p = f"model.layers.{i}"
        t[f"{p}.input_layernorm.weight"] = np.ones((D,), np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = np.ones((D,), np.float32)
        for nm, shape in [("q_proj", (H * Dh, D)), ("k_proj", (Hk * Dh, D)),
                          ("v_proj", (Hk * Dh, D)), ("o_proj", (D, H * Dh))]:
            t[f"{p}.self_attn.{nm}.weight"] = (
                rng.standard_normal(shape).astype(np.float32) * 0.1
            )
        t[f"{p}.block_sparse_moe.gate.weight"] = (
            rng.standard_normal((E, D)).astype(np.float32) * 0.1
        )
        for e in range(E):
            q = f"{p}.block_sparse_moe.experts.{e}"
            t[f"{q}.w1.weight"] = rng.standard_normal((F, D)).astype(np.float32) * 0.1
            t[f"{q}.w2.weight"] = rng.standard_normal((D, F)).astype(np.float32) * 0.1
            t[f"{q}.w3.weight"] = rng.standard_normal((F, D)).astype(np.float32) * 0.1
    os.makedirs(path, exist_ok=True)
    from safetensors.numpy import save_file as _sf

    _sf(t, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({
            "model_type": "mixtral", "vocab_size": V, "hidden_size": D,
            "intermediate_size": F, "num_hidden_layers": L,
            "num_attention_heads": H, "num_key_value_heads": Hk,
            "max_position_embeddings": cfg.max_position_embeddings,
            "num_local_experts": E, "num_experts_per_tok": 2,
        }, f)
    return t


@pytest.mark.parametrize("quantize", [None, "int8"])
def test_sharded_load_moe_expert_stacks(tmp_path, quantize):
    """VERDICT r4 item 6: expert stacks [L, E, in, out] shard-load over
    an ep x tp mesh with parity against the stacked loader — BASELINE
    config 4's (Mixtral/DeepSeek EP) real-checkpoint path."""
    from dynamo_tpu.models.loader import load_params, load_params_sharded

    cfg = _cfg(num_local_experts=4, num_experts_per_tok=2)
    path = str(tmp_path / "ckpt")
    _write_moe_checkpoint(cfg, path, seed=5)
    mesh = build_mesh(MeshConfig(ep=4, tp=2), jax.devices()[:8])
    ref = load_params(cfg, path, mesh, quantize=quantize)
    got = load_params_sharded(cfg, path, mesh, quantize=quantize)
    assert set(ref) == set(got)
    for name in sorted(ref):
        r, g = _host(ref[name]), _host(got[name])
        assert r.shape == g.shape, name
        assert r.dtype == g.dtype, name
        np.testing.assert_array_equal(r, g, err_msg=name)
        assert ref[name].sharding == got[name].sharding, name
    # the expert stacks really are ep-sharded (each device holds E/ep)
    shard = got["w_gate"].addressable_shards[0]
    assert shard.data.shape[1] == cfg.num_local_experts // 4
