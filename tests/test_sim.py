"""Discrete-event simulator internals (dynamo_tpu/sim): virtual clock
ordering, trace generator determinism, the worker service-time model,
FaultPlan re-evaluation at sim time, and fleet-level admission/
degradation behavior. The planner-in-the-loop replay tests live in
tests/test_planner.py."""

import pytest

from dynamo_tpu.faults.plan import parse_plan
from dynamo_tpu.sim import (
    FleetSim,
    LengthModel,
    SimClock,
    SimConfig,
    SimFaultDriver,
    SimLoop,
    SimWorker,
    WorkerProfile,
    bursty_trace,
    diurnal_trace,
    drive,
    merge_traces,
)

# --- core ------------------------------------------------------------------


def test_sim_loop_orders_events_and_breaks_ties_by_schedule_order():
    loop = SimLoop()
    seen = []
    loop.at(2.0, seen.append, "b")
    loop.at(1.0, seen.append, "a")
    loop.at(2.0, seen.append, "c")  # same t as "b": schedule order wins
    loop.run()
    assert seen == ["a", "b", "c"]
    assert loop.now == 2.0


def test_sim_loop_after_and_until():
    loop = SimLoop()
    seen = []
    loop.after(5.0, seen.append, 1)
    loop.after(15.0, seen.append, 2)
    loop.run(until=10.0)
    assert seen == [1] and loop.now == 10.0
    loop.run()
    assert seen == [1, 2] and loop.now == 15.0


def test_events_scheduled_in_the_past_clamp_to_now():
    loop = SimLoop()
    seen = []

    def late():
        loop.at(0.0, seen.append, "clamped")  # the past is not schedulable

    loop.at(3.0, late)
    loop.run()
    assert seen == ["clamped"] and loop.now == 3.0


def test_sim_clock_refuses_to_sleep():
    clock = SimClock(SimLoop())
    with pytest.raises(RuntimeError):
        drive(clock.sleep(1.0))


def test_drive_rejects_coroutines_that_actually_await():
    class _Pending:
        def __await__(self):
            yield

    async def pends():
        await _Pending()

    async def immediate():
        return 42

    assert drive(immediate()) == 42
    with pytest.raises(RuntimeError):
        drive(pends())


# --- traces ----------------------------------------------------------------


def test_traces_are_deterministic_and_sorted():
    a = diurnal_trace(600.0, seed=7)
    b = diurnal_trace(600.0, seed=7)
    assert a == b and len(a) > 100
    assert all(x.t <= y.t for x, y in zip(a, a[1:]))
    c = diurnal_trace(600.0, seed=8)
    assert a != c  # seed actually matters

    d = bursty_trace(600.0, seed=7)
    e = bursty_trace(600.0, seed=7)
    assert d == e and len(d) > 100
    assert all(x.t <= y.t for x, y in zip(d, d[1:]))


def test_length_model_clamps_heavy_tail():
    lm = LengthModel(prompt_max=512, output_max=256)
    trace = diurnal_trace(1200.0, seed=3, lengths=lm)
    prompts = [r.prompt_tokens for r in trace]
    outputs = [r.output_tokens for r in trace]
    assert max(prompts) <= 512 and min(prompts) >= lm.prompt_min
    assert max(outputs) <= 256 and min(outputs) >= lm.output_min
    # heavy tail: p99 well above the median
    prompts.sort()
    assert prompts[int(0.99 * len(prompts))] > 2 * prompts[len(prompts) // 2]


def test_bursty_trace_actually_bursts():
    tr = bursty_trace(
        1200.0, seed=11, calm_rps=5.0, burst_rps=80.0,
        mean_calm_s=60.0, mean_burst_s=20.0,
    )
    # per-10s arrival counts must span calm (<~100/10s) and burst rates
    buckets = [0] * 120
    for r in tr:
        buckets[min(119, int(r.t // 10))] += 1
    assert min(buckets) < 200 and max(buckets) > 400


def test_merge_traces_reassigns_ordered_unique_rids():
    a = diurnal_trace(300.0, seed=1)
    b = bursty_trace(300.0, seed=2)
    m = merge_traces(a, b)
    assert len(m) == len(a) + len(b)
    assert [r.rid for r in m] == list(range(len(m)))
    assert all(x.t <= y.t for x, y in zip(m, m[1:]))


# --- worker model ----------------------------------------------------------


def test_worker_admission_bounds_slots_and_kv():
    prof = WorkerProfile(batch_slots=2, kv_blocks=10, block_size=128)
    w = SimWorker(0, prof)
    blocks = prof.blocks_for(128, 128, spec_on=False)
    assert blocks == 2
    assert w.can_admit(blocks)
    w.admit(1, blocks)
    w.admit(2, blocks)
    assert not w.can_admit(blocks)  # slots exhausted
    w.release(1)
    assert w.can_admit(blocks)
    assert not w.can_admit(9)  # kv exhausted (4 used + 9 > 10)


def test_worker_itl_grows_with_occupancy_and_spec_speeds_it_up():
    prof = WorkerProfile(decode_tok_s_max=2000.0, n_half=16)
    w = SimWorker(0, prof)
    idle = w.itl_s(0.0, spec_on=False)
    for i in range(32):
        w.admit(i, 1)
    loaded = w.itl_s(0.0, spec_on=False)
    assert loaded > idle
    assert w.itl_s(0.0, spec_on=True) < loaded
    w.slow_until = 10.0
    w.slow_factor = 4.0
    assert w.itl_s(5.0, spec_on=False) == pytest.approx(4 * loaded)
    assert w.itl_s(15.0, spec_on=False) == pytest.approx(loaded)


def test_spec_charges_kv_overhead():
    prof = WorkerProfile(spec_kv_overhead_blocks=1)
    assert (
        prof.blocks_for(128, 128, spec_on=True)
        == prof.blocks_for(128, 128, spec_on=False) + 1
    )


# --- fault driver ----------------------------------------------------------


def test_sim_fault_driver_matches_plan_semantics():
    plan = parse_plan("seed=5;worker.liveness:kill@after=3@max=1")
    drv = SimFaultDriver(plan)
    fires = [bool(drv.due(float(i), "worker.liveness")) for i in range(8)]
    # after=3 skips the first three passes; max=1 stops after one fire
    assert fires == [False, False, False, True, False, False, False, False]
    assert drv.fired == [(3.0, "worker.liveness", "kill")]


def test_sim_fault_driver_probability_streams_are_seeded():
    plan = parse_plan("seed=42;engine.step:delay=0.5@p=0.3")
    a = SimFaultDriver(plan)
    b = SimFaultDriver(plan)
    pattern_a = [bool(a.due(i, "engine.step")) for i in range(200)]
    pattern_b = [bool(b.due(i, "engine.step")) for i in range(200)]
    assert pattern_a == pattern_b
    assert 20 < sum(pattern_a) < 100  # ~30% of 200


def test_sim_fault_driver_match_scopes_to_context():
    plan = parse_plan("seed=1;http.request:error@match=sim-7")
    drv = SimFaultDriver(plan)
    assert not drv.due(0.0, "http.request", rid="sim-1")
    assert drv.due(0.0, "http.request", rid="sim-7")


# --- fleet -----------------------------------------------------------------


def _light_trace(n=200, seed=3):
    return diurnal_trace(
        200.0, seed=seed, base_rps=1.0, peak_rps=2.0, period_s=200.0
    )[:n]


def test_fleet_completes_everything_under_light_load():
    res = FleetSim(_light_trace(), SimConfig(initial_decode=2)).run()
    assert res["requests"] == res["completed"]
    assert res["shed"] == 0 and res["unfinished"] == 0
    assert res["slo_attainment"] == 1.0
    assert res["goodput_tokens"] > 0


def test_fleet_sheds_under_flood_and_admitted_requests_still_meet_slo():
    # 200 rps into one worker: admission must shed, and what IS admitted
    # must still be served within target (the Tail-at-Scale contract)
    trace = bursty_trace(
        60.0, seed=9, calm_rps=200.0, burst_rps=200.0, mean_calm_s=1e9,
    )
    res = FleetSim(
        trace,
        SimConfig(initial_decode=1, max_queue_depth=40, slo_ttft_ms=4000.0),
    ).run()
    assert res["shed"] > 100
    assert res["completed"] > 0
    assert res["slo_attainment"] > 0.8


def test_degradation_ladder_tightens_admission_and_disables_spec():
    fleet = FleetSim(_light_trace(), SimConfig(max_queue_depth=100))
    base_queue = fleet.admission.config.max_queue_depth
    fleet.set_level(1)
    assert fleet.admission.config.max_queue_depth < base_queue
    assert fleet.spec_enabled
    fleet.set_level(2)
    assert not fleet.spec_enabled
    fleet.set_level(3)
    assert fleet.admission.config.max_queue_depth <= fleet.config.shed_queue_depth
    fleet.set_level(0)
    assert fleet.admission.config.max_queue_depth == base_queue
    assert fleet.spec_enabled


def test_http_request_faults_fail_or_delay_requests():
    trace = _light_trace(100)
    plan = parse_plan("seed=2;http.request:error@max=5")
    res = FleetSim(trace, SimConfig(initial_decode=2), plan=plan).run()
    assert res["failed_frontend"] == 5
    assert res["completed"] == res["requests"] - 5


def test_worker_kill_migrates_inflight_streams():
    """Mid-stream migration default (mirrors the live routers): a kill
    re-queues in-flight streams as resumes instead of dropping them —
    every request still completes exactly once (conservation holds with
    nothing lost)."""
    trace = diurnal_trace(
        120.0, seed=4, base_rps=10.0, peak_rps=10.0, period_s=120.0
    )
    plan = parse_plan("seed=2;worker.liveness:kill@after=30")
    res = FleetSim(trace, SimConfig(initial_decode=2), plan=plan).run()
    assert res["workers_killed"] == 1
    assert res["killed_inflight"] > 0
    # every killed stream was re-queued: mid-stream deaths as resumes,
    # pre-first-token deaths as failover replays — none lost
    assert res["resumed"] + res["refailed"] == res["killed_inflight"]
    assert res["resumed"] > 0
    assert res["lost_inflight"] == 0
    assert res["decode_workers_final"] == 1  # nobody heals a planner-less fleet
    assert res["completed"] + res["shed"] + res["unfinished"] == res["requests"]


def test_worker_kill_drops_inflight_with_migration_off():
    """migration=False restores the PR-5 behavior: every mid-stream
    death is lost and scored as an SLO miss, and the old conservation
    identity (lost requests never complete) holds."""
    trace = diurnal_trace(
        120.0, seed=4, base_rps=10.0, peak_rps=10.0, period_s=120.0
    )
    plan = parse_plan("seed=2;worker.liveness:kill@after=30")
    res = FleetSim(
        trace, SimConfig(initial_decode=2, migration=False), plan=plan
    ).run()
    assert res["workers_killed"] == 1
    assert res["killed_inflight"] > 0
    assert res["resumed"] == 0
    assert res["lost_inflight"] == res["killed_inflight"]
    assert res["completed"] + res["lost_inflight"] + res["shed"] + res[
        "unfinished"
    ] == res["requests"]


def test_pre_first_token_kill_recomputes_ttft():
    """A kill landing before the request's FIRST token is a failover,
    not a mid-stream resume: the live plane replays it from scratch, so
    the re-placement must recompute TTFT instead of keeping the dead
    placement's optimistic stamp (an emitted stream keeps its TTFT)."""
    from dynamo_tpu.sim.fleet import _InFlight
    from dynamo_tpu.sim.traces import SimRequest

    fleet = FleetSim([], SimConfig(initial_decode=2))
    fleet._spawn_worker(initial=True)
    fleet._spawn_worker(initial=True)
    rec = _InFlight(req=SimRequest(rid=1, t=0.0, prompt_tokens=64,
                                   output_tokens=50))
    fleet._inflight[1] = rec
    assert fleet._try_place(rec)
    ttft0 = rec.ttft
    # the kill lands within first_step_s: zero tokens ever streamed
    fleet._kill_worker(rec.worker)
    assert fleet.killed_inflight == 1
    assert fleet.refailed == 1 and fleet.resumed == 0
    assert rec.emitted == 0 and rec.resumed_n == 0
    # re-placed later, TTFT is the REAL (later) first-token time
    fleet.loop._now = 7.0
    assert fleet._try_place(rec)
    assert rec.ttft > ttft0
    assert rec.ttft == 7.0 - rec.req.t + fleet.config.worker.first_step_s
    # whereas a stream with delivered tokens keeps its original TTFT
    rec.emitted = 3
    fleet._kill_worker(rec.worker)
    assert rec.resumed_n == 1
    ttft_mid = rec.ttft
    fleet._spawn_worker(initial=True)  # both originals are dead now
    fleet.loop._now = 20.0
    assert fleet._try_place(rec)
    assert rec.ttft == ttft_mid


def test_migration_beats_loss_and_cache_hot_beats_cold():
    """The kill-recovery ladder the live plane implements: migration
    completes streams a kill would have lost, and a cache-hot resume
    (cheap onboard) finishes sooner than a cold re-prefill."""
    trace = diurnal_trace(
        120.0, seed=4, base_rps=10.0, peak_rps=10.0, period_s=120.0
    )

    def run(migration, hot_frac=0.0):
        plan = parse_plan("seed=2;worker.liveness:kill@after=30")
        # slow prefill makes the re-prefill cost visible in finish times
        cfg = SimConfig(
            initial_decode=2, migration=migration,
            resume_cache_hot_frac=hot_frac,
            worker=WorkerProfile(prefill_tok_s=2_000.0),
        )
        return FleetSim(trace, cfg, plan=plan).run()

    lost = run(False)
    cold = run(True, hot_frac=0.0)
    hot = run(True, hot_frac=1.0)
    assert cold["completed"] > lost["completed"]
    assert hot["resumed_hot"] == hot["resumed"] > 0
    assert cold["resumed_hot"] == 0
    # cache-hot resumes onboard instead of re-prefilling, so they don't
    # burn the (deliberately slow) prefill pool's capacity: the hot
    # fleet keeps the no-migration fleet's SLO numbers AND completes
    # the killed streams, while cold re-prefill pays visibly
    assert hot["completed"] == cold["completed"]
    assert hot["met"] > cold["met"]
    assert hot["goodput_tokens"] > cold["goodput_tokens"]
    # and determinism survives the migration path
    assert run(True, hot_frac=1.0) == hot
