"""Soak test: sustained request churn through the distributed runtime
without leaking tasks, sockets, or store state (reference:
lib/runtime/tests/soak.rs and lib/bindings/python/tests/soak.py)."""

import asyncio
import gc
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.config import RuntimeConfig
from dynamo_tpu.runtime.engine import Context, FnEngine, collect
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode
from dynamo_tpu.runtime.runtime import DistributedRuntime
from dynamo_tpu.store.memory import MemoryStore
from dynamo_tpu.store.server import StoreServer

ROUNDS = 40
CONCURRENCY = 8


async def echo_stream(request: Any, ctx: Context) -> AsyncIterator[Any]:
    for tok in request["tokens"]:
        if ctx.is_stopped:
            return
        yield {"token": tok}


async def test_soak_request_churn_no_leaks():
    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    # generous TTL: this test measures churn/leaks, not lease expiry — a
    # multi-second scheduler stall under full-suite load must not kill the
    # worker's lease (lost lease => runtime shutdown => 300s router hang)
    cfg = lambda: RuntimeConfig(  # noqa: E731
        store_host="127.0.0.1", store_port=server.port,
        worker_host="127.0.0.1", lease_ttl_s=30.0, lease_keepalive_s=0.5,
    )
    worker = await DistributedRuntime.create(config=cfg())
    frontend = await DistributedRuntime.create(config=cfg())
    try:
        ep = worker.namespace("soak").component("w").endpoint("gen")
        await ep.serve(FnEngine(echo_stream))
        client = await (
            frontend.namespace("soak").component("w").endpoint("gen").client()
        )
        await client.wait_for_instances()
        router = PushRouter(client, RouterMode.ROUND_ROBIN)

        async def one(i: int) -> int:
            items = await collect(
                router.generate({"tokens": list(range(i % 7 + 1))}, Context())
            )
            return len(items)

        baseline_tasks = None
        for r in range(ROUNDS):
            counts = await asyncio.gather(
                *[one(r * CONCURRENCY + i) for i in range(CONCURRENCY)]
            )
            assert all(c > 0 for c in counts)
            if r == 4:
                gc.collect()
                baseline_tasks = len(asyncio.all_tasks())
        gc.collect()
        await asyncio.sleep(0.1)
        # steady state: no unbounded task growth vs the warm baseline
        assert baseline_tasks is not None
        assert len(asyncio.all_tasks()) <= baseline_tasks + 4, (
            f"task leak: {len(asyncio.all_tasks())} vs baseline "
            f"{baseline_tasks}"
        )
        # store state stays bounded: only this worker's registrations
        entries = await frontend.store.kv_get_prefix("soak/")
        assert len(entries) <= 4, [e.key for e in entries]
    finally:
        await worker.shutdown()
        await frontend.shutdown()
        await server.stop()
