"""Speculative decoding tests (dynamo_tpu/spec).

The load-bearing properties:
- greedy speculative output is BIT-IDENTICAL to greedy non-speculative
  output on the tiny model (acceptance criterion of the subsystem);
- seeded statistical check that rejection sampling preserves the target
  distribution reference_sample_numpy/softmax describes;
- rollback bookkeeping: staged drafts never leak into host token state,
  blocks, or the prefix cache.
"""

import asyncio
import os

import numpy as np
import pytest

from dynamo_tpu.engine.allocator import BlockAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.sampling import SamplingBatch, reference_sample_numpy
from dynamo_tpu.engine.scheduler import Scheduler, Sequence
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.spec import BigramTableDrafter, NgramDrafter, build_drafter
from dynamo_tpu.tokens import TokenBlockSequence

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


# ---------------------------------------------------------------------------
# Drafters
# ---------------------------------------------------------------------------


def test_ngram_drafter_prompt_lookup():
    d = NgramDrafter(max_ngram=3)
    hist = [1, 2, 3, 4, 5, 6, 1, 2, 3]
    # trailing [1,2,3] matched at the start; continuation follows it
    assert d.propose(hist, 4) == [4, 5, 6, 1]
    assert d.propose(hist, 2) == [4, 5]
    # no earlier occurrence -> no proposal
    assert d.propose([7, 8, 9], 3) == []
    # k=0 and tiny histories are no-ops
    assert d.propose(hist, 0) == []
    assert d.propose([1], 3) == []


def test_ngram_drafter_prefers_longest_and_most_recent_match():
    d = NgramDrafter(max_ngram=3)
    # [2,3] occurs twice; the trailing trigram [1,2,3] only at index 0
    hist = [1, 2, 3, 9, 2, 3, 7, 1, 2, 3]
    assert d.propose(hist, 1) == [9]  # trigram match wins over bigram
    # drop to bigrams: most RECENT earlier [2,3] is at index 4 -> 7
    assert NgramDrafter(max_ngram=2).propose(hist, 1) == [7]


def test_ngram_drafter_window_bounds_scan():
    """The matcher reads only the trailing ``max_window`` tokens (the
    engine materializes exactly that tail via tail_tokens): matches
    older than the window are invisible."""
    hist = [1, 2, 3, 4, 5] + [9] * 50 + [1, 2, 3]
    assert NgramDrafter(max_ngram=3).propose(hist, 2) == [4, 5]
    small = NgramDrafter(max_ngram=3, max_window=8)
    assert small.window == 8
    # engine-side windowing: the drafter only ever sees the tail
    assert small.propose(hist[-8:], 2) == []


def test_tail_tokens_walks_blocks_from_the_end():
    seq = TokenBlockSequence(list(range(10)), block_size=4)
    assert seq.tail_tokens(3) == [7, 8, 9]
    assert seq.tail_tokens(6) == [4, 5, 6, 7, 8, 9]  # crosses a block
    assert seq.tail_tokens(100) == list(range(10))
    assert seq.tail_tokens(0) == []
    assert seq.last_token() == 9


def test_bigram_drafter_table_and_files(tmp_path):
    b = BigramTableDrafter.from_corpus([1, 2, 3, 1, 2, 3, 1, 2], 10)
    assert b.propose([9, 1], 3) == [2, 3, 1]
    assert b.propose([7], 2) == []  # no entry for 7
    assert b.propose([], 2) == []
    # json round trip
    import json

    p = tmp_path / "bigram.json"
    p.write_text(json.dumps({"1": 2, "2": 3}))
    j = BigramTableDrafter.from_file(str(p))
    assert j.propose([1], 3) == [2, 3]
    # npz round trip
    pz = tmp_path / "bigram.npz"
    np.savez(pz, next=b.table)
    assert BigramTableDrafter.from_file(str(pz)).propose([9, 1], 3) == [2, 3, 1]


def test_build_drafter_specs(tmp_path):
    assert isinstance(build_drafter("ngram"), NgramDrafter)
    assert build_drafter("ngram:5").max_ngram == 5
    with pytest.raises(ValueError):
        build_drafter("bigram")  # needs a path
    with pytest.raises(ValueError):
        build_drafter("medusa")


# ---------------------------------------------------------------------------
# Rejection sampling: distribution preservation (seeded, statistical)
# ---------------------------------------------------------------------------


def _verify(logits, tokens, draft_lens, opts, seeds):
    import jax.numpy as jnp

    from dynamo_tpu.spec.verify import verify_tokens

    sb = SamplingBatch.from_options(opts, seeds)
    t, lp, n = verify_tokens(
        jnp.asarray(logits), jnp.asarray(np.asarray(tokens, np.int32)),
        jnp.asarray(np.asarray(draft_lens, np.int32)), sb.arrays,
    )
    return np.asarray(t), np.asarray(lp), np.asarray(n), sb


def test_spec_rejection_preserves_target_distribution():
    """P(emit x at position j) must equal the target softmax regardless
    of what the drafter proposed — N independent seeded verifies over
    the same logits, frequencies vs reference_sample_numpy's transform."""
    V, S, K = 13, 4, 3
    rng = np.random.default_rng(42)
    base = (rng.normal(size=(S, V)) * 1.5).astype(np.float32)
    # draft 0 = a high-probability token (so the conditional position-1
    # sample survives often); draft 1 deliberately unlikely
    p_row0 = np.exp(base[0] - base[0].max())
    d0 = int(np.argmax(p_row0))
    drafts = [d0, int(np.argmin(p_row0)), 3]
    N = 4000
    logits = np.broadcast_to(base, (N, S, V)).astype(np.float32)
    tokens = np.zeros((N, S), np.int32)
    tokens[:, 1:] = drafts
    opts = [SamplingOptions(temperature=1.0)] * N
    t, _, n, sb = _verify(logits, tokens, [K] * N, opts, list(range(N)))

    # position 0 marginal == softmax of the reference transform
    ref = reference_sample_numpy(base[0], sb.arrays, 0)
    p0 = np.exp(ref - ref.max())
    p0 /= p0.sum()
    freq0 = np.bincount(t[:, 0], minlength=V) / N
    assert np.abs(freq0 - p0).max() < 0.03, (freq0, p0)

    # conditioned on draft 0 accepted, position 1 marginal == its target
    # (acceptance happens with prob p0(d0) ≈ 0.2 here — enough samples
    # for a 4-sigma band at this vocab size)
    m = n > 1
    assert m.sum() > 500
    p1 = np.exp(base[1].astype(np.float64) - base[1].max())
    p1 /= p1.sum()
    freq1 = np.bincount(t[m, 1], minlength=V) / m.sum()
    assert np.abs(freq1 - p1).max() < 0.07, (freq1, p1)


def test_spec_verify_respects_topk_filter():
    """With top_k the emitted token must come from the SAME keep set
    sample() filters to — never a token outside the top-k slice."""
    V, S = 17, 3
    rng = np.random.default_rng(7)
    base = (rng.normal(size=(S, V)) * 2).astype(np.float32)
    N = 512
    logits = np.broadcast_to(base, (N, S, V)).astype(np.float32)
    topk = 3
    keep0 = set(np.argsort(base[0])[-topk:].tolist())
    tokens = np.zeros((N, S), np.int32)
    tokens[:, 1] = int(np.argsort(base[0])[0])  # draft OUTSIDE the keep set
    tokens[:, 2] = 1
    opts = [SamplingOptions(temperature=1.0, top_k=topk)] * N
    t, _, n, _ = _verify(logits, tokens, [S - 1] * N, opts, list(range(N)))
    # the out-of-set draft must always be rejected, and the replacement
    # drawn from the keep set
    assert (n >= 1).all()
    assert set(t[:, 0].tolist()) <= keep0
    assert (t[:, 0] != tokens[0, 1]).all()


def test_spec_verify_greedy_rows_and_zero_drafts():
    V, S = 9, 4
    rng = np.random.default_rng(3)
    logits = (rng.normal(size=(2, S, V)) * 3).astype(np.float32)
    gt = np.argmax(logits, axis=-1)
    tokens = np.zeros((2, S), np.int32)
    tokens[0, 1:] = gt[0, :3]  # perfect drafts -> full accept + bonus
    opts = [SamplingOptions(use_greedy=True)] * 2
    t, lp, n, _ = _verify(logits, tokens, [3, 0], opts, [1, 2])
    assert n[0] == 4 and (t[0] == gt[0]).all()
    # zero drafts = plain greedy decode of one token
    assert n[1] == 1 and t[1, 0] == gt[1, 0]
    # emitted logprobs are log_softmax of the raw logits at the chosen ids
    lsm = logits[0, 0] - np.log(np.exp(logits[0, 0]).sum())
    np.testing.assert_allclose(lp[0, 0], lsm[t[0, 0]], rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Scheduler bookkeeping: staging, rollback, block accounting
# ---------------------------------------------------------------------------


def _mk_seq(tokens, block_size=4, max_tokens=None, request_id="r"):
    return Sequence(
        request=PreprocessedRequest(
            request_id=request_id,
            token_ids=list(tokens),
            stop=StopConditions(max_tokens=max_tokens),
        ),
        tokens=TokenBlockSequence(list(tokens), block_size=block_size),
    )


def test_reserve_spec_tokens_allocates_and_shrinks():
    alloc = BlockAllocator(8, 4)  # 7 usable
    sched = Scheduler(alloc, 4, max_batch_size=4)
    seq = _mk_seq(list(range(7)))  # 7 tokens -> 2 blocks
    seq.block_table = [alloc.allocate_block(), alloc.allocate_block()]
    # 3 drafts need a 3rd block (7+3=10 tokens -> 3 blocks); 5 free
    k = sched.reserve_spec_tokens(seq, [11, 12, 13])
    assert k == 3 and len(seq.block_table) == 3
    assert seq.total_len == 10  # drafts staged into token state
    seq.tokens.unwind(k)
    assert seq.total_len == 7
    # exhaust the pool: a seq at a block boundary gets 0 drafts
    while alloc.num_free:
        alloc.allocate_block()
    seq2 = _mk_seq(list(range(4)), request_id="r2")
    seq2.block_table = [1]  # exactly full block
    assert sched.reserve_spec_tokens(seq2, [5, 6]) == 0
    assert seq2.total_len == 4  # nothing staged
    # a seq with slack in its last block keeps what fits
    seq3 = _mk_seq(list(range(6)), request_id="r3")
    seq3.block_table = [2, 3]  # covers 8 slots, 2 spare
    assert sched.reserve_spec_tokens(seq3, [7, 8, 9]) == 2
    assert seq3.total_len == 8


def test_build_spec_arrays_geometry():
    alloc = BlockAllocator(64, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8)
    seq = _mk_seq(list(range(6)), request_id="a")
    seq.block_table = [alloc.allocate_block() for _ in range(2)]
    k = sched.reserve_spec_tokens(seq, [21, 22])
    assert k == 2
    arrays = sched.build_spec_arrays([(seq, [5, 21, 22])], S=4)
    B, S = arrays["tokens"].shape
    assert S == 4 and B == sched._decode_batch(1)
    # row = [last committed token, d0, d1, pad]
    assert arrays["tokens"][0, :3].tolist() == [5, 21, 22]
    # positions contiguous from the carry token, pads included
    assert arrays["positions"][0].tolist() == [5, 6, 7, 8]
    assert arrays["context_lens"][0] == 8
    assert arrays["draft_lens"][0] == 2
    # real slots resolve through the block table; the pad writes to the
    # reserved garbage slot 0
    bt = seq.block_table
    assert arrays["slot_mapping"][0] == bt[1] * 4 + 1
    assert arrays["slot_mapping"][3] == 0
    seq.tokens.unwind(k)


# ---------------------------------------------------------------------------
# Engine end-to-end (async, CPU)
# ---------------------------------------------------------------------------


def _engine_config(**kw) -> EngineConfig:
    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=256,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, prompt_ids, max_tokens=8, request_id="r",
                    speculative=None, temperature=None):
    sampling = (
        SamplingOptions(use_greedy=True)
        if temperature is None
        else SamplingOptions(temperature=temperature, seed=7)
    )
    req = PreprocessedRequest(
        request_id=request_id,
        token_ids=list(prompt_ids),
        sampling=sampling,
        stop=StopConditions(max_tokens=max_tokens),
        speculative=speculative,
    )
    out = []
    final = None
    async for item in engine.as_async_engine().generate(req, Context()):
        out.extend(item.token_ids)
        if item.is_final:
            final = item
    return out, final


# a prompt whose greedy continuation reuses its own structure: the
# n-gram drafter then actually proposes (and a wrong-draft path is
# still exercised whenever the model diverges from the lookup)
SPEC_PROMPT = [1, 2, 3, 4, 5, 6, 1, 2, 3, 4, 5, 6, 1, 2, 3]


async def test_engine_greedy_spec_bit_identical():
    """THE acceptance criterion: greedy speculative == greedy plain,
    token for token, including an odd max_tokens (bonus-token clamping)
    — and the drafter must have actually proposed something. The plain
    reference runs on the SAME engine via the per-request opt-out,
    which diverts to the literal non-speculative decode path (same
    kernels, same state; greedy continuation through the warm prefix
    cache is pinned identical by test_engine.py). Piggybacks the
    temperature-sampled completion and the /metrics exposition checks
    (tier-1 budget: engine launches are the expensive part here)."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.telemetry import REGISTRY

    engine = await JaxEngine.launch(
        _engine_config(spec_decode="ngram", spec_tokens=4)
    )
    try:
        spec, fs = await _generate(engine, SPEC_PROMPT, max_tokens=13,
                                   request_id="spec")
        assert fs.finish_reason == FinishReason.LENGTH
        assert fs.completion_tokens == 13 == len(spec)
        assert engine.spec_proposed_total > 0
        # per-request opt-out = the plain decode path: same output
        base, _ = await _generate(engine, SPEC_PROMPT, max_tokens=13,
                                  request_id="off", speculative=False)
        assert spec == base
        # temperature sampling rides the verify step too (distribution
        # correctness is the statistical test's job; here: exact token
        # accounting and clean teardown)
        toks, fin = await _generate(engine, SPEC_PROMPT, max_tokens=10,
                                    request_id="sampled", temperature=0.8)
        assert len(toks) == 10 and fin.completion_tokens == 10
        # a prompt with no self-similarity: zero-proposal steps fall
        # back to the plain decode step and serving still completes
        toks, fin = await _generate(engine, list(range(40, 51)),
                                    max_tokens=6, request_id="noprop")
        assert len(toks) == 6 and fin.completion_tokens == 6
        # all blocks returned (drafted blocks uncommitted + freed)
        assert not engine.scheduler.running
    finally:
        await engine.shutdown()
    # accept-rate and proposed/accepted instruments appear on /metrics
    text = REGISTRY.render()
    assert 'dynamo_spec_proposed_tokens_total{drafter="ngram"}' in text
    assert "dynamo_spec_accept_rate" in text
    assert "dynamo_spec_step_seconds" in text


@pytest.mark.slow
async def test_engine_spec_concurrent_and_prefix_cache_intact():
    """Speculative KV writes for rejected drafts must never poison the
    prefix cache: continuing from a previously-generated history through
    the cache must match a fresh engine's continuation."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = await JaxEngine.launch(
        _engine_config(spec_decode="ngram", spec_tokens=3, block_size=4)
    )
    try:
        prompts = [SPEC_PROMPT, list(range(2, 12)), [3, 3, 3, 3, 3, 3, 3]]
        results = await asyncio.gather(*[
            _generate(engine, p, max_tokens=8, request_id=f"c{i}")
            for i, p in enumerate(prompts)
        ])
        for toks, fin in results:
            assert len(toks) == 8 and fin.finish_reason == FinishReason.LENGTH
        # reuse the full first history through the warm prefix cache
        full = prompts[0] + results[0][0]
        cont_cached, _ = await _generate(engine, full, max_tokens=4,
                                         request_id="reuse")
    finally:
        await engine.shutdown()
    fresh = await JaxEngine.launch(_engine_config(block_size=4))
    try:
        cont_fresh, _ = await _generate(fresh, full, max_tokens=4,
                                        request_id="fresh")
    finally:
        await fresh.shutdown()
    assert cont_cached == cont_fresh


def test_spec_divert_policy():
    """ANY opted-out request diverts its whole batch: the opt-out
    contract is the literal plain-decode path (T==1 kernel, sample()'s
    RNG stream), which the verify step only approximates."""
    from dynamo_tpu.engine.engine import JaxEngine

    engine = JaxEngine(_engine_config(spec_decode="ngram"))
    engine._drafter = NgramDrafter()

    def seq(greedy, spec):
        return Sequence(
            request=PreprocessedRequest(
                request_id="x", token_ids=[1, 2],
                sampling=SamplingOptions(
                    use_greedy=greedy,
                    temperature=None if greedy else 0.9,
                ),
                speculative=spec,
            ),
            tokens=TokenBlockSequence([1, 2], block_size=4),
        )

    spec_on = seq(True, None)
    assert not engine._spec_divert([spec_on, seq(False, None)])
    assert engine._spec_divert([spec_on, seq(True, False)])
    assert engine._spec_divert([spec_on, seq(False, False)])
    assert engine._spec_divert([seq(True, False)])


async def test_spec_config_rejects_fused_windows_and_bad_k():
    from dynamo_tpu.engine.engine import JaxEngine

    with pytest.raises(ValueError, match="decode_steps"):
        await JaxEngine.launch(
            _engine_config(spec_decode="ngram", decode_steps=4)
        )
    with pytest.raises(ValueError, match="spec_tokens"):
        await JaxEngine.launch(
            _engine_config(spec_decode="ngram", spec_tokens=0)
        )


# ---------------------------------------------------------------------------
# KV-router satellite: token-specific in-flight release
# ---------------------------------------------------------------------------


def test_kv_scheduler_note_done_releases_specific_charge():
    from dynamo_tpu.kv_router.indexer import KvIndexer
    from dynamo_tpu.kv_router.scheduler import KvMetricsAggregator, KvScheduler

    sched = KvScheduler(KvIndexer(block_size=4), KvMetricsAggregator())
    t1 = sched.note_dispatch(7)
    t2 = sched.note_dispatch(7)
    # releasing the SECOND charge must keep the first alive
    sched.note_done(7, t2)
    assert sched.inflight[7] == [t1]
    # double-release of the same token is a no-op
    sched.note_done(7, t2)
    assert sched.inflight[7] == [t1]
    sched.note_done(7, t1)
    assert 7 not in sched.inflight
    # unknown worker is a no-op
    sched.note_done(99, 1.0)
    # schedule() hands the token back on the decision
    sched.aggregator.update(
        __import__(
            "dynamo_tpu.kv_router.protocols", fromlist=["ForwardPassMetrics"]
        ).ForwardPassMetrics(worker_id=1)
    )
    d = sched.schedule([1, 2, 3, 4], [1])
    assert d.dispatch_token > 0
    assert sched.inflight[1] == [d.dispatch_token]
    sched.note_done(1, d.dispatch_token)
    assert 1 not in sched.inflight
