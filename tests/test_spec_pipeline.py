"""Overlapped speculative decoding (docs/speculative_decoding.md,
pipelined section): spec (PR 3) composed with the decode pipeline's
double-buffering (PR 7).

The load-bearing properties:
- spec+overlap output is BIT-IDENTICAL to serial spec (--no-overlap) —
  greedy AND seeded-sampled (the sampled realization depends on the
  proposal stream, so this pins that pre-draft/repair reproduces the
  serial drafts byte-for-byte) — and greedy rows additionally match a
  plain non-speculative engine;
- the incremental per-sequence n-gram index proposes EXACTLY what the
  from-scratch windowed scan proposes, across appends, unwinds and
  speculative suffixes;
- late-detected stops discard in-flight spec tokens (blocks freed,
  prefix cache clean), zero-proposal steps fall back without deadlock,
  and the attribution ledger's fractions still sum to 1.0 over a
  pipelined spec run.

CPU-runnable tier-1, like tests/test_spec.py and tests/test_overlap.py.
"""

import asyncio
import os
import random

import numpy as np
import pytest

from dynamo_tpu.engine.allocator import BlockAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.scheduler import Scheduler, Sequence
from dynamo_tpu.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.spec import NgramDrafter
from dynamo_tpu.tokens import TokenBlockSequence

MODEL_DIR = os.path.join(os.path.dirname(__file__), "data", "tiny_llama_model")


# ---------------------------------------------------------------------------
# Incremental n-gram index == from-scratch build (satellite)
# ---------------------------------------------------------------------------


def test_ngram_index_matches_scratch_fuzz():
    """The exactness contract: across random append/unwind/propose
    sequences (small vocab to force gram collisions, windows small
    enough to roll), the incremental index proposes byte-identically to
    the from-scratch windowed scan — including speculative suffixes
    (the pipeline's pre-draft/repair contexts)."""
    rng = random.Random(12)
    for trial in range(40):
        vocab = rng.choice([3, 4, 8])
        window = rng.choice([6, 16, 64])
        d = NgramDrafter(
            max_ngram=rng.choice([2, 3, 4]), min_ngram=1, max_window=window
        )
        toks = [rng.randrange(vocab) for _ in range(rng.randrange(0, 40))]
        idx = d.make_index(toks[-window:], len(toks))
        for _ in range(40):
            op = rng.random()
            if op < 0.55:
                new = [rng.randrange(vocab) for _ in range(rng.randrange(1, 6))]
                toks += new
                idx.extend(new)
            elif op < 0.7 and toks:
                # unwind/truncation: the engine rebuilds from the tail
                n = rng.randrange(1, min(5, len(toks)) + 1)
                toks = toks[:-n]
                idx = d.make_index(toks[-window:], len(toks))
            sfx = [rng.randrange(vocab) for _ in range(rng.randrange(0, 6))]
            k = rng.randrange(1, 6)
            want = d.propose((toks[-window:] + sfx)[-window:], k)
            got = idx.propose(k, sfx)
            assert got == want, (trial, toks, sfx, k, want, got)


def test_ngram_index_compaction_keeps_answers():
    """Long generations compact the retained token list to the window;
    proposals before and after compaction match the scratch scan."""
    d = NgramDrafter(max_ngram=3, max_window=16)
    toks = []
    idx = d.make_index([], 0)
    rng = random.Random(5)
    for _ in range(20):  # 20 × 5 tokens ≫ 2 × window → several compactions
        new = [rng.randrange(4) for _ in range(5)]
        toks += new
        idx.extend(new)
        assert idx.propose(4) == d.propose(toks[-16:], 4)
    assert len(idx.tokens) <= 2 * 16


# ---------------------------------------------------------------------------
# plan_pipelined_spec geometry / rollback (scheduler units)
# ---------------------------------------------------------------------------


def _mk_seq(tokens, block_size=4, max_tokens=None, request_id="r"):
    return Sequence(
        request=PreprocessedRequest(
            request_id=request_id,
            token_ids=list(tokens),
            stop=StopConditions(max_tokens=max_tokens),
        ),
        tokens=TokenBlockSequence(list(tokens), block_size=block_size),
    )


def test_plan_pipelined_spec_lag_shifts_geometry():
    from dynamo_tpu.engine.scheduler import SeqState

    alloc = BlockAllocator(64, 4)
    sched = Scheduler(alloc, 4, max_batch_size=8)
    seq = _mk_seq(list(range(6)), request_id="a")
    seq.state = SeqState.RUNNING
    seq.block_table = [alloc.allocate_block() for _ in range(2)]
    # the just-harvested step emitted 2 tokens (lag) not yet appended;
    # the repaired drafts for the next step are [21, 22]
    plan = sched.plan_pipelined_spec([(seq, 2, [21, 22])], S=4)
    assert plan is not None
    a = plan["arrays"]
    # carry sits at (total_len + lag) - 1 = 7; drafts follow
    assert a["positions"][0].tolist() == [7, 8, 9, 10]
    assert a["tokens"][0, 1:3].tolist() == [21, 22]
    assert a["tokens"][0, 0] == 0  # placeholder: device chain fills it
    assert a["context_lens"][0] == 6 + 2 + 2
    assert a["draft_lens"][0] == 2
    assert plan["offsets"] == [2]  # seed offset = lag
    # blocks grew to cover total+lag+k = 10 tokens -> 3 blocks
    assert len(seq.block_table) == 3
    # the carry slot resolves through the block table at position 7
    assert a["slot_mapping"][0] == seq.block_table[1] * 4 + 3


def test_plan_pipelined_spec_excludes_predicted_finishes_and_rolls_back():
    from dynamo_tpu.engine.scheduler import SeqState

    alloc = BlockAllocator(8, 4)  # 7 usable
    sched = Scheduler(alloc, 4, max_batch_size=8)
    done = _mk_seq(list(range(4)), max_tokens=2, request_id="done")
    done.state = SeqState.RUNNING
    done.generated = 1
    done.block_table = [alloc.allocate_block()]
    live = _mk_seq(list(range(4)), request_id="live")
    live.state = SeqState.RUNNING
    live.block_table = [alloc.allocate_block()]
    # `done` finishes inside its lag (generated 1 + lag 1 == max 2):
    # not a row of the next step
    plan = sched.plan_pipelined_spec(
        [(done, 1, [9]), (live, 1, [9, 9])], S=4
    )
    assert plan is not None
    assert [s.request_id for s, _ in plan["works"]] == ["live"]
    assert plan["src_idx"][0] == 1  # chains from the PREVIOUS row index
    # cancellation flushes (returns None)
    live.is_cancelled = lambda: True
    assert sched.plan_pipelined_spec([(live, 1, [9])], S=4) is None
    live.is_cancelled = None
    # block exhaustion rolls back and flushes
    free0 = alloc.num_free
    while alloc.num_free:
        alloc.allocate_block()
    big = _mk_seq(list(range(4)), request_id="big")
    big.state = SeqState.RUNNING
    big.block_table = [1]
    blocks0 = len(big.block_table)
    assert sched.plan_pipelined_spec([(big, 1, [7, 7, 7])], S=4) is None
    assert len(big.block_table) == blocks0  # rollback left no growth


# ---------------------------------------------------------------------------
# Engine end-to-end (async, CPU)
# ---------------------------------------------------------------------------


def _engine_config(**kw) -> EngineConfig:
    defaults = dict(
        model_path=MODEL_DIR,
        model_name="tiny",
        random_weights=True,
        num_blocks=128,
        block_size=8,
        max_batch_size=8,
        prefill_chunk_size=32,
        max_model_len=256,
        spec_decode="ngram",
        spec_tokens=4,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, prompt_ids, max_tokens=8, request_id="r",
                    temperature=None, seed=7, context=None):
    sampling = (
        SamplingOptions(use_greedy=True)
        if temperature is None
        else SamplingOptions(temperature=temperature, seed=seed)
    )
    req = PreprocessedRequest(
        request_id=request_id,
        token_ids=list(prompt_ids),
        sampling=sampling,
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    out = []
    final = None
    async for item in engine.as_async_engine().generate(
        req, context or Context()
    ):
        out.extend(item.token_ids)
        if item.is_final:
            final = item
    return out, final


# a prompt whose greedy continuation reuses its own structure, so the
# n-gram drafter proposes (and the pre-draft can hit); the other two
# exercise partial/no self-similarity in the same batch
SPEC_PROMPT = [1, 2, 3, 4, 5, 6, 1, 2, 3, 4, 5, 6, 1, 2, 3]
PROMPTS = [SPEC_PROMPT, [2, 9, 2, 9, 2, 9, 2], list(range(30, 41))]


async def _decode_all(engine, max_tokens=11, temperature=None, seed=7):
    outs = await asyncio.gather(*[
        _generate(engine, p, max_tokens=max_tokens, request_id=f"r{i}",
                  temperature=temperature, seed=seed)
        for i, p in enumerate(PROMPTS)
    ])
    return [o[0] for o in outs]


async def test_spec_overlap_bit_identical_vs_serial_spec():
    """THE acceptance criterion (ISSUE 12): spec+overlap greedy AND
    seeded-sampled output bit-identical to serial spec (--no-overlap),
    token for token — and the pipeline actually engaged (pipelined spec
    steps recorded, proposals made). Greedy output additionally matches
    a plain non-speculative engine (spec never changes greedy output).
    """
    from dynamo_tpu.engine.engine import JaxEngine

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        over = await _decode_all(eng)
        over_sampled = await _decode_all(eng, temperature=0.8)
        assert eng.spec_pipeline_steps > 0, "pipeline never engaged"
        assert eng.spec_proposed_total > 0
        dbg = eng.debug_state()["spec"]
        assert dbg["pipelined"] is True
        assert dbg["predraft_hits"] + dbg["predraft_misses"] > 0
    finally:
        await eng.shutdown()

    eng = await JaxEngine.launch(_engine_config(overlap=False))
    try:
        serial = await _decode_all(eng)
        serial_sampled = await _decode_all(eng, temperature=0.8)
        assert eng.spec_pipeline_steps == 0
        assert eng.spec_proposed_total > 0
    finally:
        await eng.shutdown()
    assert over == serial
    assert over_sampled == serial_sampled
    assert all(len(o) == 11 for o in over)

    # greedy rows also match plain non-speculative greedy
    plain = await JaxEngine.launch(_engine_config(spec_decode=""))
    try:
        base = await _decode_all(plain)
    finally:
        await plain.shutdown()
    assert over == base


async def test_spec_pipeline_late_stop_discards_inflight_tokens():
    """Late-detected stop (cancel/deadline): tokens sampled past the
    stop are DISCARDED at emit — never appended, never content-
    addressed — blocks are freed, and a continuation through the warm
    prefix cache matches a fresh engine's."""
    from dynamo_tpu.engine.engine import JaxEngine

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        free0 = eng.allocator.num_free
        ctx = Context()
        req = PreprocessedRequest(
            request_id="late-stop",
            token_ids=SPEC_PROMPT,
            sampling=SamplingOptions(use_greedy=True),
            stop=StopConditions(max_tokens=64, ignore_eos=True),
        )
        got = []
        async for item in eng.as_async_engine().generate(req, ctx):
            got.extend(item.token_ids)
            if len(got) >= 2:
                ctx.stop_generating()  # a stop-string detection's shape
                break
        await eng.wait_for_state(
            lambda e: not e.scheduler.running and not e.scheduler.waiting
            and not e.scheduler.prefilling
        )
        await eng.wait_for_state(lambda e: e.allocator.num_free == free0)
        cont_warm, _ = await _generate(
            eng, SPEC_PROMPT + got, max_tokens=4, request_id="cont"
        )
    finally:
        await eng.shutdown()
    fresh = await JaxEngine.launch(_engine_config(spec_decode=""))
    try:
        cont_fresh, _ = await _generate(
            fresh, SPEC_PROMPT + got, max_tokens=4, request_id="cont2"
        )
    finally:
        await fresh.shutdown()
    assert cont_warm == cont_fresh


async def test_spec_pipeline_zero_proposal_falls_back_without_deadlock():
    """Prompts with no self-similarity produce zero proposals: the
    pipeline must fall back to the plain step (serial, one step) and
    keep serving — no deadlock, full token counts, and speculation
    re-engages when a proposal-rich request arrives."""
    from dynamo_tpu.engine.engine import JaxEngine

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        toks, fin = await _generate(eng, list(range(40, 51)),
                                    max_tokens=6, request_id="noprop")
        assert len(toks) == 6 and fin.completion_tokens == 6
        # proposal-rich follow-up: the spec pipeline engages after the
        # zero-proposal episode
        toks, fin = await _generate(eng, SPEC_PROMPT, max_tokens=9,
                                    request_id="rich")
        assert len(toks) == 9
        assert eng.spec_pipeline_steps > 0
        assert not eng.scheduler.running
    finally:
        await eng.shutdown()


async def test_spec_pipeline_attribution_fracs_sum_to_one():
    """The ledger's partition stays exact under overlapped spec steps:
    bucket fractions sum to 1.0 (±0.05) over an e2e pipelined run, the
    window saw 'spec'-kind records, and the draft-hidden gauge is
    exposed on /metrics."""
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.telemetry import REGISTRY

    eng = await JaxEngine.launch(_engine_config(overlap=True))
    try:
        await _decode_all(eng, max_tokens=12)
        assert eng.spec_pipeline_steps > 0
        w = eng.attribution.window_summary()
        total = sum(w["frac"].values())
        assert w["steps"] > 0
        assert abs(total - 1.0) < 0.05, w["frac"]
        snap = eng.attribution.snapshot()
        assert any(r["kind"] == "spec" for r in snap["recent"])
        dbg = eng.debug_state()["spec"]
        assert dbg["draft_hidden_s"] >= 0.0
        assert 0.0 <= dbg["draft_hidden_frac"] <= 1.0
    finally:
        await eng.shutdown()
    text = REGISTRY.render()
    assert "dynamo_spec_draft_hidden_frac" in text


async def test_spec_pipeline_respects_block_pressure():
    """Block exhaustion mid-pipeline flushes to the serial spec step
    (which shrinks draft runs instead of preempting): output under
    pressure equals a roomy engine's greedy output."""
    from dynamo_tpu.engine.engine import JaxEngine

    async def run(num_blocks):
        eng = await JaxEngine.launch(
            _engine_config(overlap=True, num_blocks=num_blocks)
        )
        try:
            outs = await asyncio.gather(*[
                _generate(eng, p, max_tokens=10, request_id=f"p{i}")
                for i, p in enumerate(PROMPTS[:2])
            ])
            return [o[0] for o in outs]
        finally:
            await eng.shutdown()

    tight = await run(10)
    roomy = await run(64)
    assert tight == roomy
    assert all(len(t) == 10 for t in tight)
