"""Control-plane store tests: memory semantics + TCP server/client parity.

Mirrors the reference's strategy of exercising real-but-local control-plane
processes (reference: lib/bindings/python/tests/test_kv_bindings.py spawns
real nats-server+etcd); here the coordinator runs in-process on a loopback
socket.
"""

import asyncio

import pytest

from dynamo_tpu.store.base import subject_matches
from dynamo_tpu.store.client import StoreClient
from dynamo_tpu.store.memory import MemoryStore
from dynamo_tpu.store.server import StoreServer


def test_subject_matching():
    assert subject_matches("a.b.c", "a.b.c")
    assert not subject_matches("a.b.c", "a.b.d")
    assert subject_matches("a.*.c", "a.x.c")
    assert not subject_matches("a.*.c", "a.x.y.c")
    assert subject_matches("a.>", "a.b.c.d")
    assert not subject_matches("a.>", "a")
    assert subject_matches(">", "anything.at.all")


async def _exercise_kv(store):
    assert await store.kv_get("x") is None
    v1 = await store.kv_put("x", b"1")
    e = await store.kv_get("x")
    assert e.value == b"1" and e.version == v1
    assert await store.kv_create("x", b"2") is False  # CAS: exists
    assert await store.kv_create("y", b"2") is True
    entries = await store.kv_get_prefix("")
    assert {e.key for e in entries} == {"x", "y"}
    assert await store.kv_delete("x") is True
    assert await store.kv_delete("x") is False


async def _exercise_watch(store):
    await store.kv_put("ns/a", b"1")
    watch = await store.watch_prefix("ns/")
    assert [e.key for e in watch.snapshot()] == ["ns/a"]
    await store.kv_put("ns/b", b"2")
    await store.kv_delete("ns/a")
    await store.kv_put("other/c", b"3")  # outside prefix: no event
    it = watch.__aiter__()
    ev1 = await asyncio.wait_for(it.__anext__(), 5)
    assert ev1.type == "put" and ev1.entry.key == "ns/b"
    ev2 = await asyncio.wait_for(it.__anext__(), 5)
    assert ev2.type == "delete" and ev2.entry.key == "ns/a"
    await watch.close()


async def _exercise_lease(store):
    lid = await store.lease_grant(ttl_s=0.4)
    await store.kv_put("lease/k1", b"v", lease_id=lid)
    watch = await store.watch_prefix("lease/")
    assert len(watch.snapshot()) == 1
    # keepalive holds it
    for _ in range(3):
        await asyncio.sleep(0.2)
        assert await store.lease_keepalive(lid) is True
    assert await store.kv_get("lease/k1") is not None
    # stop keepalives: expiry deletes the key and notifies the watcher
    it = watch.__aiter__()
    ev = await asyncio.wait_for(it.__anext__(), 5)
    assert ev.type == "delete" and ev.entry.key == "lease/k1"
    assert await store.kv_get("lease/k1") is None
    assert await store.lease_keepalive(lid) is False
    await watch.close()


async def _exercise_pubsub(store):
    sub = await store.subscribe("events.*")
    await store.publish("events.kv", b"hello")
    await store.publish("unrelated.kv", b"nope")
    it = sub.__aiter__()
    subject, payload = await asyncio.wait_for(it.__anext__(), 5)
    assert subject == "events.kv" and payload == b"hello"
    await sub.close()


async def _exercise_queue(store):
    assert await store.queue_pop("q1", timeout_s=0.05) is None
    await store.queue_push("q1", b"job1")
    await store.queue_push("q1", b"job2")
    assert await store.queue_len("q1") == 2
    m1 = await store.queue_pop("q1", timeout_s=1)
    assert m1.payload == b"job1"
    assert await store.queue_ack("q1", m1.id) is True
    # unacked message gets redelivered after visibility timeout
    m2 = await store.queue_pop("q1", timeout_s=1, visibility_s=0.3)
    assert m2.payload == b"job2"
    m2b = await store.queue_pop("q1", timeout_s=5)
    assert m2b.payload == b"job2"  # redelivered
    await store.queue_ack("q1", m2b.id)
    assert await store.queue_len("q1") == 0


async def _exercise_objects(store):
    blob = b"\x00\x01" * 1000
    await store.obj_put("models", "card.json", blob)
    assert await store.obj_get("models", "card.json") == blob
    assert await store.obj_list("models") == ["card.json"]
    assert await store.obj_delete("models", "card.json") is True
    assert await store.obj_get("models", "card.json") is None


EXERCISES = [
    _exercise_kv,
    _exercise_watch,
    _exercise_lease,
    _exercise_pubsub,
    _exercise_queue,
    _exercise_objects,
]


@pytest.mark.parametrize("exercise", EXERCISES, ids=lambda f: f.__name__)
async def test_memory_store(exercise):
    store = MemoryStore(lease_sweep_interval_s=0.1)
    try:
        await exercise(store)
    finally:
        await store.close()


@pytest.mark.parametrize("exercise", EXERCISES, ids=lambda f: f.__name__)
async def test_tcp_store(exercise):
    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    client = await StoreClient.connect(port=server.port)
    try:
        await exercise(client)
    finally:
        await client.close()
        await server.stop()


async def test_tcp_disconnect_revokes_leases():
    """Dropping the client connection revokes its leases — the liveness
    primitive workers rely on (≈ reference etcd lease expiry on crash)."""
    server = StoreServer(MemoryStore(lease_sweep_interval_s=0.1), port=0)
    await server.start()
    observer = await StoreClient.connect(port=server.port)
    worker = await StoreClient.connect(port=server.port)
    lid = await worker.lease_grant(ttl_s=60)
    await worker.kv_put("instances/w1", b"alive", lease_id=lid)
    watch = await observer.watch_prefix("instances/")
    assert len(watch.snapshot()) == 1
    await worker.close()  # simulate crash
    it = watch.__aiter__()
    ev = await asyncio.wait_for(it.__anext__(), 5)
    assert ev.type == "delete" and ev.entry.key == "instances/w1"
    await observer.close()
    await server.stop()


async def test_tcp_concurrent_queue_pop_does_not_block_connection():
    """A blocking queue_pop must not stall other requests on the connection."""
    server = StoreServer(MemoryStore(), port=0)
    await server.start()
    client = await StoreClient.connect(port=server.port)
    pop_task = asyncio.create_task(client.queue_pop("jobs", timeout_s=5))
    await asyncio.sleep(0.05)
    # unary op completes while pop is pending
    assert await asyncio.wait_for(client.kv_put("k", b"v"), 2) > 0
    await client.queue_push("jobs", b"work")
    msg = await asyncio.wait_for(pop_task, 2)
    assert msg.payload == b"work"
    await client.close()
    await server.stop()


async def test_kv_put_reattaches_lease_ownership():
    """Re-registering a key under a new lease detaches it from the old one:
    the stale lease's expiry must not delete the live registration."""
    store = MemoryStore(lease_sweep_interval_s=0.05)
    try:
        old = await store.lease_grant(ttl_s=0.2)
        await store.kv_put("instances/w", b"v1", lease_id=old)
        new = await store.lease_grant(ttl_s=60)
        await store.kv_put("instances/w", b"v2", lease_id=new)
        await asyncio.sleep(0.5)  # old lease expires and is swept
        e = await store.kv_get("instances/w")
        assert e is not None and e.value == b"v2"
        await store.lease_keepalive(new)
    finally:
        await store.close()
