"""Coordinator-store durability: WAL + snapshot replay.

The reference's control plane rides etcd (raft-durable) and JetStream
(file store) — a coordinator restart there loses nothing but leases
(reference: lib/runtime/src/transports/{etcd,nats}.rs). The self-hosted
store must honor the same contract: model registrations, deployment
specs, prefill queues, and the G4 object plane survive a restart;
lease-attached liveness keys do not (their owners re-register).
"""

import asyncio
import os

import pytest

from dynamo_tpu.store.memory import MemoryStore


async def _fill(store: MemoryStore) -> int:
    await store.kv_put("models/llama", b"card-payload")
    await store.kv_put("deployments/d1", b"spec")
    lease = await store.lease_grant(30.0)
    await store.kv_put("instances/worker-1", b"alive", lease_id=lease)
    for i in range(5):
        await store.queue_push("prefill", f"req-{i}".encode())
    # one popped-but-unacked (must come back READY), one acked (gone)
    m_acked = await store.queue_pop("prefill", timeout_s=1)
    await store.queue_ack("prefill", m_acked.id)
    m_inflight = await store.queue_pop("prefill", timeout_s=1)
    assert m_inflight is not None
    await store.obj_put("kv-tier", "block-123", b"\x00\x01" * 64)
    await store.obj_put("kv-tier", "block-456", b"\x02" * 16)
    await store.obj_delete("kv-tier", "block-456")
    return m_acked.id


async def _verify(store: MemoryStore, acked_id: int) -> None:
    assert (await store.kv_get("models/llama")).value == b"card-payload"
    assert (await store.kv_get("deployments/d1")).value == b"spec"
    # leased liveness keys are ephemeral by design
    assert await store.kv_get("instances/worker-1") is None
    # 5 pushed - 1 acked = 4 ready (the unacked in-flight one came back)
    assert await store.queue_len("prefill") == 4
    seen = set()
    for _ in range(4):
        m = await store.queue_pop("prefill", timeout_s=1)
        seen.add(m.payload)
    assert f"req-0".encode() not in seen or acked_id != 1
    assert len(seen) == 4
    assert await store.obj_get("kv-tier", "block-123") == b"\x00\x01" * 64
    assert await store.obj_get("kv-tier", "block-456") is None
    assert await store.obj_list("kv-tier") == ["block-123"]


async def test_restart_replays_wal(tmp_path):
    path = str(tmp_path / "store.wal")
    s1 = MemoryStore(persist_path=path)
    acked = await _fill(s1)
    # crash: no close(), restart replays the raw WAL
    s1._wal.close()
    s2 = MemoryStore(persist_path=path)
    await _verify(s2, acked)
    await s2.close()


async def test_restart_after_clean_close_uses_snapshot(tmp_path):
    path = str(tmp_path / "store.wal")
    s1 = MemoryStore(persist_path=path)
    acked = await _fill(s1)
    await s1.close()  # compacts into a snapshot, truncates the WAL
    assert os.path.getsize(path) == 0
    assert os.path.getsize(path + ".snap") > 0
    s2 = MemoryStore(persist_path=path)
    await _verify(s2, acked)
    # survives a SECOND restart after more mutations on top of the snap
    await s2.kv_put("models/llama", b"v2")
    await s2.queue_push("prefill", b"late")
    s2._wal.close()
    s3 = MemoryStore(persist_path=path)
    assert (await s3.kv_get("models/llama")).value == b"v2"
    assert await s3.queue_len("prefill") == 5
    await s3.close()


async def test_compaction_bounds_log_growth(tmp_path):
    path = str(tmp_path / "store.wal")
    s = MemoryStore(persist_path=path)
    s._wal.compact_bytes = 2048  # tiny threshold
    for i in range(200):
        await s.kv_put(f"k/{i % 10}", b"x" * 32)
    assert s._wal.size < 4096  # compaction kept folding the log
    s._wal.close()
    s2 = MemoryStore(persist_path=path)
    for i in range(10):
        assert (await s2.kv_get(f"k/{i}")).value == b"x" * 32
    await s2.close()


async def test_torn_tail_write_is_tolerated(tmp_path):
    path = str(tmp_path / "store.wal")
    s = MemoryStore(persist_path=path)
    await s.kv_put("good", b"1")
    s._wal.close()
    with open(path, "a") as f:
        f.write('{"op":"kv_put","k":"torn"')  # crash mid-record
    s2 = MemoryStore(persist_path=path)
    assert (await s2.kv_get("good")).value == b"1"
    assert await s2.kv_get("torn") is None
    await s2.close()


async def test_crash_between_snapshot_and_truncate_no_duplicates(tmp_path):
    """compact() is replace-then-truncate; a crash in between leaves the
    pre-compaction log next to the fresh snapshot. Replay must not
    double-deliver queue messages the snapshot already folded in."""
    path = str(tmp_path / "store.wal")
    s = MemoryStore(persist_path=path)
    for i in range(3):
        await s.queue_push("q", f"m{i}".encode())
    log_copy = open(path).read()  # pre-compaction log
    await s.close()  # compact: snapshot written, log truncated
    # simulate the crash: restore the stale log beside the new snapshot
    with open(path, "w") as f:
        f.write(log_copy)
    s2 = MemoryStore(persist_path=path)
    assert await s2.queue_len("q") == 3  # not 6
    await s2.close()


async def test_compaction_triggered_by_push_keeps_the_push(tmp_path):
    """The compaction triggered by a queue_push's own WAL append must
    snapshot state that already contains that message."""
    path = str(tmp_path / "store.wal")
    s = MemoryStore(persist_path=path)
    s._wal.compact_bytes = 1  # every append compacts
    await s.queue_push("q", b"only")
    s._wal.close()
    s2 = MemoryStore(persist_path=path)
    assert await s2.queue_len("q") == 1
    assert (await s2.queue_pop("q", timeout_s=1)).payload == b"only"
    await s2.close()


async def test_leased_overwrite_tombstones_durable_value(tmp_path):
    """A leased put shadowing a durable key must not let a restart
    resurrect the stale durable value."""
    path = str(tmp_path / "store.wal")
    s = MemoryStore(persist_path=path)
    await s.kv_put("svc/endpoint", b"v1")  # durable
    lease = await s.lease_grant(30.0)
    await s.kv_put("svc/endpoint", b"v2", lease_id=lease)  # live re-registration
    s._wal.close()
    s2 = MemoryStore(persist_path=path)
    # live store would serve v2-or-nothing; stale v1 must NOT come back
    assert await s2.kv_get("svc/endpoint") is None
    await s2.close()


# ---------------------------------------------------------------------------
# Native (C++) server: kill-and-restart must preserve the same state the
# python store does (native/store/store_server.cc snapshot persistence).
# ---------------------------------------------------------------------------

import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(REPO, "dynamo_tpu", "native", "dynamo_store")


def _spawn_native(persist: str):
    proc = subprocess.Popen(
        [BINARY, "--host", "127.0.0.1", "--port", "0",
         "--persist-path", persist],
        stdout=subprocess.PIPE,
    )
    line = proc.stdout.readline()
    assert line.startswith(b"LISTENING"), line
    return proc, int(line.split()[1])


async def test_native_store_restart_preserves_state(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "native", "build.py")],
        capture_output=True, text=True,
    )
    if not os.path.exists(BINARY):
        pytest.skip(f"native store build unavailable: {r.stderr[-200:]}")
    from dynamo_tpu.store.client import StoreClient

    persist = str(tmp_path / "native.snap")
    proc, port = _spawn_native(persist)
    try:
        c = await StoreClient.connect("127.0.0.1", port)
        await c.kv_put("models/m", b"card")
        lease = await c.lease_grant(30.0)
        await c.kv_put("instances/w1", b"alive", lease_id=lease)
        for i in range(3):
            await c.queue_push("prefill", f"r{i}".encode())
        m = await c.queue_pop("prefill", timeout_s=1)
        await c.queue_ack("prefill", m.id)
        await c.obj_put("bkt", "obj1", b"\x01\x02")
        await c.close()
    finally:
        # graceful stop -> final snapshot
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=10) == 0

    proc, port = _spawn_native(persist)
    try:
        c = await StoreClient.connect("127.0.0.1", port)
        assert (await c.kv_get("models/m")).value == b"card"
        assert await c.kv_get("instances/w1") is None  # leased: ephemeral
        assert await c.queue_len("prefill") == 2
        seen = {(await c.queue_pop("prefill", timeout_s=1)).payload
                for _ in range(2)}
        assert len(seen) == 2 and m.payload not in seen
        assert await c.obj_get("bkt", "obj1") == b"\x01\x02"
        await c.close()
    finally:
        proc.kill()
        proc.wait()
