"""Unit tests for dynamo_tpu.telemetry: spans + tracer + context
propagation, the metrics registry, and the Perfetto/Chrome export."""

import json
import math
import threading

import pytest

from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.telemetry import (
    NULL_SPAN,
    JsonlSpanExporter,
    Registry,
    Tracer,
    check_scrape_safety,
    get_tracer,
    reset_tracer,
)
from dynamo_tpu.telemetry.export import (
    build_span_tree,
    load_spans,
    to_chrome_trace,
)


class ListExporter:
    def __init__(self):
        self.spans = []

    def export(self, span):
        self.spans.append(span)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_disabled_tracer_returns_null_span():
    t = Tracer()
    assert not t.enabled
    s = t.span("x")
    assert s is NULL_SPAN
    s.set_attr("a", 1)  # all no-ops
    s.end()
    assert s.trace_context() is None
    with t.span("y") as s2:
        assert s2 is NULL_SPAN


def test_span_parenting_and_export():
    t = Tracer()
    sink = ListExporter()
    t.add_exporter(sink)
    with t.span("root", attrs={"service": "frontend"}) as root:
        with t.span("child", parent=root) as child:
            child.set_attr("k", "v")
    assert [s.name for s in sink.spans] == ["child", "root"]
    c, r = sink.spans
    assert c.trace_id == r.trace_id
    assert c.parent_id == r.span_id
    assert r.parent_id is None
    assert c.attrs["k"] == "v"
    assert c.duration_s is not None and c.duration_s >= 0


def test_span_parent_from_dict_and_context():
    t = Tracer()
    sink = ListExporter()
    t.add_exporter(sink)
    s1 = t.span("a", parent={"trace_id": "t1", "span_id": "p1"})
    s1.end()
    assert s1.trace_id == "t1" and s1.parent_id == "p1"
    # runtime Context carries trace ids and acts as a parent
    ctx = Context(trace_id="t2", span_id="p2")
    s2 = t.span("b", parent=ctx)
    s2.end()
    assert s2.trace_id == "t2" and s2.parent_id == "p2"
    # and adopts a span as its trace
    ctx2 = Context()
    assert ctx2.trace_context() is None
    ctx2.set_trace(s2)
    assert ctx2.trace_id == "t2" and ctx2.span_id == s2.span_id
    # child() propagates the trace link
    assert ctx2.child().trace_id == "t2"


def test_record_explicit_timestamps():
    t = Tracer()
    sink = ListExporter()
    t.add_exporter(sink)
    sid = t.record(
        "engine.decode", start=123.0, duration_s=0.5,
        parent={"trace_id": "tt", "span_id": "pp"}, attrs={"tokens": 7},
    )
    assert sid
    (s,) = sink.spans
    assert s.start == 123.0 and s.duration_s == 0.5
    assert s.trace_id == "tt" and s.parent_id == "pp"


def test_sampling_zero_drops_roots_but_keeps_propagated():
    t = Tracer(sample=0.0)
    sink = ListExporter()
    t.add_exporter(sink)
    assert t.span("root") is NULL_SPAN
    # inbound context: the head already sampled this trace IN
    s = t.span("child", parent={"trace_id": "t", "span_id": "p"})
    assert s is not NULL_SPAN
    s.end()
    assert len(sink.spans) == 1


def test_negative_sampling_decision_propagates():
    """A head's sampled-OUT mark must suppress downstream spans — a
    worker with its own (sample=1.0) tracer must not start orphan
    roots for a request the frontend dropped."""
    # head: sampling off
    head = Tracer(sample=0.0)
    head_sink = ListExporter()
    head.add_exporter(head_sink)
    root = head.span("http.request")
    assert root is NULL_SPAN
    ctx = Context()
    ctx.set_trace(root)  # no-op: NULL carries nothing
    ctx.trace_sampled = False  # what the frontend sets explicitly
    assert ctx.trace_context() == {"sampled": False}
    # the mark survives the wire round-trip and child()
    assert ctx.child().trace_context() == {"sampled": False}
    # downstream: fully-sampling tracer stays quiet for this request
    worker = Tracer(sample=1.0)
    worker_sink = ListExporter()
    worker.add_exporter(worker_sink)
    assert worker.span("worker.generate", parent=ctx) is NULL_SPAN
    assert worker.record(
        "engine.decode", start=1.0, duration_s=0.1,
        parent=ctx.trace_context(),
    ) is None
    assert not worker_sink.spans
    # ...but an untraced request (no decision at all) may still root
    assert worker.span("worker.generate", parent=Context()) is not NULL_SPAN


def test_propagation_context_rules(monkeypatch, tmp_path):
    """One helper owns the boundary rules: real span wins; NULL span
    passes the inbound through (incl. a negative mark); NULL span at an
    enabled head propagates {"sampled": False}; disabled → None."""
    from dynamo_tpu.telemetry import propagation_context

    reset_tracer()
    try:
        # disabled tracer, no inbound: no decision
        assert propagation_context(NULL_SPAN) is None
        # disabled tracer, inbound context: passed through verbatim
        inbound = {"trace_id": "t", "span_id": "p"}
        assert propagation_context(NULL_SPAN, inbound) == inbound
        assert propagation_context(NULL_SPAN, {"sampled": False}) == {
            "sampled": False
        }
        ctx = Context(trace_id="t", span_id="p")
        assert propagation_context(NULL_SPAN, ctx) == inbound
        # enabled tracer, NULL span, no inbound: we are the head and
        # sampling dropped the root — negative mark propagates
        monkeypatch.setenv("DYN_TRACE_FILE", str(tmp_path / "p.jsonl"))
        reset_tracer()
        assert propagation_context(NULL_SPAN) == {"sampled": False}
        # a real span always wins
        span = get_tracer().span("x")
        assert propagation_context(span, inbound) == span.trace_context()
        span.end()
    finally:
        reset_tracer()


def test_remote_prefill_request_schema_tolerance():
    """Queue payload compat both ways: old payloads lack `trace`, and a
    NEWER sender's unknown keys must not crash this reader."""
    from dynamo_tpu.disagg.protocols import RemotePrefillRequest

    old = json.dumps({
        "request_id": "r", "token_ids": [1], "block_size": 4,
        "transfer_key": "k",
    }).encode()
    assert RemotePrefillRequest.from_bytes(old).trace is None
    future = json.dumps({
        "request_id": "r", "token_ids": [1], "block_size": 4,
        "transfer_key": "k", "trace": {"sampled": False},
        "some_future_field": 42,
    }).encode()
    req = RemotePrefillRequest.from_bytes(future)
    assert req.trace == {"sampled": False}


def test_choice_fanout_context_keeps_trace():
    """n>1 per-choice contexts must carry the parent's trace link (and
    a head's negative sampling mark) through to the engine."""
    from dynamo_tpu.preprocessor.fanout import _ChoiceContext

    parent = Context(trace_id="t9", span_id="s9")
    parent.trace_sampled = True
    child = _ChoiceContext(parent)
    assert child.trace_context() == {"trace_id": "t9", "span_id": "s9"}
    dropped = Context()
    dropped.trace_sampled = False
    assert _ChoiceContext(dropped).trace_context() == {"sampled": False}


def test_exception_inside_span_sets_error_attr():
    t = Tracer()
    sink = ListExporter()
    t.add_exporter(sink)
    with pytest.raises(RuntimeError):
        with t.span("boom"):
            raise RuntimeError("x")
    assert sink.spans[0].attrs["error"] == "RuntimeError"


def test_jsonl_exporter_roundtrip(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    t = Tracer()
    t.add_exporter(JsonlSpanExporter(path))
    with t.span("root") as root:
        t.span("child", parent=root).end()
    spans = load_spans([path])
    assert {s["name"] for s in spans} == {"root", "child"}
    by_name = {s["name"]: s for s in spans}
    assert by_name["child"]["parent_id"] == by_name["root"]["span_id"]


def test_get_tracer_env_wiring(tmp_path, monkeypatch):
    path = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("DYN_TRACE_FILE", path)
    reset_tracer()
    try:
        tr = get_tracer()
        assert tr.enabled
        tr.span("e").end()
        assert load_spans([path])[0]["name"] == "e"
    finally:
        reset_tracer()
    monkeypatch.delenv("DYN_TRACE_FILE")
    reset_tracer()
    assert not get_tracer().enabled
    reset_tracer()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_basics():
    r = Registry()
    c = r.counter("t_requests_total", "help", labels=("model",))
    c.labels("m").inc()
    c.labels("m").inc(2)
    g = r.gauge("t_gauge", "help")
    g.set(3.5)
    g.inc()
    h = r.histogram("t_lat_seconds", "help", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(99)
    text = r.render()
    assert 't_requests_total{model="m"} 3' in text
    assert "t_gauge 4.5" in text
    # le values keep prometheus_client's formatting (series identity):
    # integral bounds render "1.0", never "1"
    assert 't_lat_seconds_bucket{le="0.1"} 1' in text
    assert 't_lat_seconds_bucket{le="1.0"} 2' in text
    assert 'le="1"}' not in text
    assert 't_lat_seconds_bucket{le="+Inf"} 3' in text
    assert "t_lat_seconds_count 3" in text
    # strict parser accepts our own output
    from prom_parser import parse

    parse(text)


def test_forbidden_label_names_rejected():
    r = Registry()
    with pytest.raises(ValueError, match="cardinality"):
        r.counter("t_bad_total", "help", labels=("request_id",))
    with pytest.raises(ValueError, match="needs help"):
        r.counter("t_nohelp_total", "")


def test_duplicate_registration_idempotent_but_conflict_raises():
    r = Registry()
    a = r.counter("t_x_total", "help", labels=("l",))
    b = r.counter("t_x_total", "help", labels=("l",))
    assert a is b
    with pytest.raises(ValueError, match="re-registered"):
        r.gauge("t_x_total", "help")


def test_label_escaping_renders_and_parses():
    r = Registry()
    c = r.counter("t_esc_total", "help", labels=("v",))
    c.labels('we"ird\\na\nme').inc()
    text = r.render()
    from prom_parser import parse

    fams = parse(text)
    key, = fams["t_esc_total"].samples
    assert dict(key[1])["v"] == 'we"ird\\na\nme'


def test_series_overflow_collapses():
    r = Registry()
    c = r.counter("t_of_total", "help", labels=("k",), max_series=4)
    for i in range(10):
        c.labels(str(i)).inc()
    assert c.num_series <= 5  # 4 real + 1 overflow
    text = r.render()
    assert "_overflow" in text


def test_check_scrape_safety_flags_bad_registry():
    r = Registry()
    ok = r.counter("t_fine_total", "help", labels=("model",))
    ok.labels("m").inc()
    check_scrape_safety(r)  # passes
    # sneak a forbidden label past the constructor
    bad = object.__new__(type(ok))
    bad.__dict__.update(ok.__dict__)
    bad.name = "t_smuggled_total"
    bad.label_names = ("request_id",)
    r._metrics["t_smuggled_total"] = bad
    with pytest.raises(ValueError, match="forbidden label"):
        check_scrape_safety(r)


def test_thread_safety_of_counter():
    r = Registry()
    c = r.counter("t_mt_total", "help")

    def work():
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.labels().value == 40_000


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def test_chrome_trace_export(tmp_path):
    path = str(tmp_path / "s.jsonl")
    t = Tracer()
    t.add_exporter(JsonlSpanExporter(path))
    with t.span("http.request", attrs={"service": "frontend"}) as root:
        t.span("engine.decode", parent=root,
               attrs={"service": "engine"}).end()
    spans = load_spans([path])
    tree = build_span_tree(spans)
    (trace,) = tree.values()
    assert len(trace["roots"]) == 1
    assert trace["roots"][0]["name"] == "http.request"
    chrome = to_chrome_trace(spans)
    complete = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"http.request", "engine.decode"}
    for e in complete:
        assert e["ts"] >= 0 and e["dur"] >= 0
    # json-serializable end to end
    json.dumps(chrome)


def test_load_spans_skips_torn_lines(tmp_path):
    p = tmp_path / "torn.jsonl"
    p.write_text(
        json.dumps({"name": "a", "trace_id": "t", "span_id": "s",
                    "start": 1.0, "duration_s": 0.1}) + "\n"
        + '{"name": "b", "trace'  # torn final line (SIGKILL mid-write)
    )
    spans = load_spans([str(p)])
    assert [s["name"] for s in spans] == ["a"]


def test_cli_trace_export(tmp_path, capsys):
    from dynamo_tpu.cli.main import main

    path = str(tmp_path / "s.jsonl")
    t = Tracer()
    t.add_exporter(JsonlSpanExporter(path))
    t.span("root").end()
    out = str(tmp_path / "chrome.json")
    with pytest.raises(SystemExit) as exc:
        main(["trace", "export", path, "-o", out])
    assert exc.value.code == 0
    data = json.loads(open(out).read())
    assert any(e["name"] == "root" for e in data["traceEvents"])


def test_histogram_math_nan_free():
    r = Registry()
    h = r.histogram("t_h_seconds", "help", buckets=(1.0,))
    h.observe(math.inf)  # lands in +Inf bucket, sum becomes inf
    text = r.render()
    assert 't_h_seconds_bucket{le="+Inf"} 1' in text
