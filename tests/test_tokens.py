"""Tests for token block hashing (≈ reference lib/llm/src/tokens.rs tests)."""

import numpy as np
import pytest

from dynamo_tpu.tokens import (
    TokenBlockSequence,
    chain_hash,
    compute_block_hash,
    compute_block_hashes_for_seq,
    compute_seq_hashes,
)


def test_block_hash_deterministic():
    a = compute_block_hash([1, 2, 3, 4])
    b = compute_block_hash(np.array([1, 2, 3, 4], dtype=np.uint32))
    assert a == b
    assert compute_block_hash([1, 2, 3, 5]) != a


def test_salt_changes_hash():
    assert compute_block_hash([1, 2, 3], salt=1) != compute_block_hash([1, 2, 3], salt=2)


def test_chained_seq_hash_prefix_property():
    """Same prefix => same chained hashes; divergence changes all later ones."""
    toks_a = list(range(64))
    toks_b = list(range(48)) + [999] + list(range(49, 64))
    ha = compute_block_hashes_for_seq(toks_a, 16)
    hb = compute_block_hashes_for_seq(toks_b, 16)
    assert ha[:3] == hb[:3]
    assert ha[3] != hb[3]
    sa = compute_seq_hashes(ha)
    sb = compute_seq_hashes(hb)
    assert sa[:3] == sb[:3]
    assert sa[3] != sb[3]


def test_seq_hash_position_sensitivity():
    """Identical block contents at different positions hash differently (chained)."""
    toks = [7] * 32
    bh = compute_block_hashes_for_seq(toks, 16)
    assert bh[0] == bh[1]  # content hash identical
    sh = compute_seq_hashes(bh)
    assert sh[0] != sh[1]  # chained hash differs


def test_sequence_append_extend():
    seq = TokenBlockSequence(block_size=4)
    assert len(seq) == 0
    completed = seq.extend([1, 2, 3])
    assert completed == []
    b = seq.append(4)
    assert b is not None
    assert b.tokens == (1, 2, 3, 4)
    assert seq.num_complete_blocks == 1
    assert len(seq) == 4
    seq.extend([5, 6, 7, 8, 9])
    assert seq.num_complete_blocks == 2
    assert len(seq) == 9
    assert seq.all_tokens() == [1, 2, 3, 4, 5, 6, 7, 8, 9]


def test_sequence_matches_batch_hashing():
    toks = list(range(100))
    seq = TokenBlockSequence(toks, block_size=16)
    assert seq.block_hashes() == compute_block_hashes_for_seq(toks, 16)
    assert seq.sequence_hashes() == compute_seq_hashes(seq.block_hashes())


def test_truncate_and_unwind():
    toks = list(range(40))
    seq = TokenBlockSequence(toks, block_size=16)
    seq.truncate(20)
    assert seq.all_tokens() == toks[:20]
    assert seq.num_complete_blocks == 1
    # hashes of kept blocks unchanged
    assert seq.block_hashes() == compute_block_hashes_for_seq(toks[:20], 16)
    # re-extending reproduces the original hashes
    seq.extend(toks[20:])
    assert seq.block_hashes() == compute_block_hashes_for_seq(toks, 16)
    seq.unwind(8)
    assert len(seq) == 32
    assert seq.num_complete_blocks == 2


def test_truncate_rebuilds_partial_parent():
    seq = TokenBlockSequence(list(range(33)), block_size=16)
    seq.truncate(17)
    seq.extend(list(range(17, 33)))
    ref = TokenBlockSequence(list(range(33)), block_size=16)
    assert seq.sequence_hashes() == ref.sequence_hashes()


def test_invalid_args():
    with pytest.raises(ValueError):
        TokenBlockSequence(block_size=0)
    seq = TokenBlockSequence([1, 2, 3], block_size=2)
    with pytest.raises(ValueError):
        seq.truncate(10)
