"""Serve-phase transfer fence (ISSUE 16): units in the compile-fence
mold plus the e2e acceptance cases — a prewarmed greedy generate runs
CLEAN under DYN_TRANSFER_FENCE=fatal (the explicit device_put staging
satisfies the armed guard), and a deliberately unstaged dispatch
produces EXACTLY ONE flight-recorder ``serve_transfer`` record, one
black-box bundle, and a Prometheus counter bump that agrees with
``/debug/state``."""

import glob
import os

import pytest

from dynamo_tpu.utils import transfer_fence

MODEL_DIR = os.path.join(
    os.path.dirname(__file__), "data", "tiny_llama_model"
)


@pytest.fixture
def fence():
    transfer_fence.set_mode("record")
    transfer_fence.reset()
    yield transfer_fence
    transfer_fence.set_mode(None)
    transfer_fence.disarm()
    transfer_fence.reset()


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_fence_mode_resolution(monkeypatch):
    transfer_fence.set_mode(None)
    monkeypatch.delenv("DYN_TRANSFER_FENCE", raising=False)
    assert transfer_fence.mode() == "off"
    assert not transfer_fence.enabled()
    for raw, want in (
        ("1", "record"), ("true", "record"), ("record", "record"),
        ("fatal", "fatal"), ("garbage", "off"), ("", "off"),
    ):
        transfer_fence.set_mode(None)
        monkeypatch.setenv("DYN_TRANSFER_FENCE", raw)
        assert transfer_fence.mode() == want
    transfer_fence.set_mode(None)


def test_intercept_recognizes_guard_errors_only(fence):
    guard = RuntimeError(
        "Disallowed host-to-device transfer: aval=ShapedArray(int32[8])"
    )
    assert fence.intercept(guard) is True
    events, n = fence.drain()
    assert n == 1 and "host-to-device" in events[0]["error"]
    # non-guard RuntimeErrors and non-RuntimeErrors pass through
    assert fence.intercept(RuntimeError("unrelated dispatch crash")) is False
    assert fence.intercept(ValueError("Disallowed host-to-device transfer")) is False
    assert fence.drain() == ([], 0)
    assert fence.stats()["events_total"] == 1  # lifetime count survives


def test_intercept_sanctioned_inside_allow_window(fence):
    exc = RuntimeError("Disallowed device-to-host transfer: aval=...")
    with fence.allow():
        assert fence.intercept(exc) is False
    assert fence.drain() == ([], 0)
    assert fence.intercept(exc) is True  # outside the window it counts


def test_fence_disabled_is_inert_and_pending_is_bounded(fence):
    fence.set_mode("off")
    assert fence.intercept(
        RuntimeError("Disallowed host-to-device transfer")
    ) is False
    assert fence.stats()["events_total"] == 0
    fence.set_mode("record")
    for i in range(200):
        fence.intercept(
            RuntimeError(f"Disallowed host-to-device transfer #{i}")
        )
    assert fence.stats()["pending"] <= 64  # deque(maxlen): DL007 holds
    events, n = fence.drain()
    assert n == 200 and len(events) <= 64  # true count survives overflow
    assert fence.fatal() is False
    fence.set_mode("fatal")
    assert fence.fatal() is True


def test_arm_flips_transfer_guard_and_disarm_restores(fence):
    import jax
    import jax.numpy as jnp
    import numpy as np

    assert fence.arm() is True
    assert fence.stats()["armed"] is True
    try:
        dev = jax.device_put(np.arange(4, dtype=np.int32))  # explicit: fine
        with pytest.raises(RuntimeError, match="Disallowed"):
            # implicit host->device upload into a jitted add
            jax.jit(lambda a, b: a + b)(
                np.arange(4, dtype=np.int32), dev
            )
        # the prewarm window's thread-local allow overrides the guard
        with fence.allow():
            jnp.asarray(np.arange(4, dtype=np.int32)) + dev
    finally:
        fence.disarm()
    assert fence.stats()["armed"] is False
    jax.jit(lambda a, b: a + b)(np.arange(4, dtype=np.int32), dev)


def test_arm_is_noop_when_disabled(fence):
    fence.set_mode("off")
    assert fence.arm() is False
    assert fence.stats()["armed"] is False


# ---------------------------------------------------------------------------
# e2e acceptance
# ---------------------------------------------------------------------------


async def test_fence_e2e_clean_then_induced_transfer_dumps_once(
    tmp_path, fence
):
    """ISSUE 16 acceptance: under the armed fence a prewarmed greedy
    generate completes with ZERO serve_transfer records (the staging
    path is the sanctioned spelling), and a dispatch with the staging
    bypassed trips the guard — exactly one flight-recorder record, one
    black-box bundle, and a counter that agrees with /debug/state."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.telemetry.instruments import TRANSFER_FENCE_EVENTS

    counter0 = TRANSFER_FENCE_EVENTS.labels().value

    async def gen(engine, rid, **samp):
        req = PreprocessedRequest(
            request_id=rid, token_ids=list(range(1, 9)),
            sampling=SamplingOptions(**samp),
            stop=StopConditions(max_tokens=2),
        )
        out = []
        async for item in engine.as_async_engine().generate(req, Context()):
            out.extend(item.token_ids)
        return out

    # fatal mode for the clean leg: any implicit transfer in the
    # prewarmed greedy path would take the engine down loudly
    fence.set_mode("fatal")
    engine = await JaxEngine.launch(EngineConfig(
        model_path=MODEL_DIR, model_name="tiny", random_weights=True,
        num_blocks=128, block_size=8, max_batch_size=8,
        prefill_chunk_size=32, max_model_len=256,
        prewarm=True, overlap=False,
        flight_dump_dir=str(tmp_path),
    ))
    try:
        assert fence.stats()["armed"] is True

        def fence_records():
            return [r for r in engine.recorder.snapshot(256)
                    if r["kind"] == "serve_transfer"]

        def bundles():
            return glob.glob(str(tmp_path / "dynamo_blackbox_*"))

        out = await gen(engine, "clean", use_greedy=True)
        assert out, "prewarmed greedy generate produced no tokens"
        assert fence_records() == [] and bundles() == []
        assert fence.stats()["events_total"] == 0

        # induced violation: bypass the explicit staging for ONE
        # dispatch — the raw numpy feed is the implicit upload the
        # fence exists to catch. record mode: escalate, then recover.
        fence.set_mode("record")
        orig = engine._stage_step_inputs
        leaked = {"n": 0}

        def leaky(arrays, sampling):
            if leaked["n"] == 0:
                leaked["n"] += 1
                return arrays, sampling
            return orig(arrays, sampling)

        engine._stage_step_inputs = leaky
        try:
            out = await gen(engine, "leaky", use_greedy=True)
        finally:
            engine._stage_step_inputs = orig
        assert out, "engine did not recover after the induced violation"
        assert leaked["n"] == 1

        recs = fence_records()
        assert len(recs) == 1, recs
        assert recs[0]["transfers"] >= 1
        assert "transfer" in recs[0]["error"].lower()
        assert len(bundles()) == 1, bundles()

        state = engine.debug_state()["transfer_fence"]
        assert state["events_total"] >= 1
        assert (
            TRANSFER_FENCE_EVENTS.labels().value - counter0
            == state["events_total"]
        )
    finally:
        await engine.shutdown()
