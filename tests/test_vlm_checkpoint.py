"""Real VLM checkpoint path: a LLaVA-layout directory (nested
text_config, ``language_model.``-prefixed LLM weights, CLIP vision
tower + multi_modal_projector safetensors) must load end to end —
config resolution, language weights, vision tower — and produce
deterministic image embeddings (reference: examples/multimodal serves
real VLM checkpoints; VERDICT r2 missing #5)."""

import json
import os

import numpy as np
import pytest

from dynamo_tpu.models.config import ModelConfig

TEXT = dict(
    model_type="llama", vocab_size=128, hidden_size=32,
    intermediate_size=64, num_hidden_layers=2, num_attention_heads=4,
    num_key_value_heads=2, max_position_embeddings=128,
)
VISION = dict(
    image_size=8, patch_size=2, hidden_size=16, intermediate_size=32,
    num_hidden_layers=2, num_attention_heads=2, layer_norm_eps=1e-5,
)


@pytest.fixture
def vlm_dir(tmp_path):
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)
    d = TEXT["hidden_size"]
    f = TEXT["intermediate_size"]
    v = TEXT["vocab_size"]
    hkd = TEXT["num_key_value_heads"] * (d // TEXT["num_attention_heads"])
    vd, vf, vp = VISION["hidden_size"], VISION["intermediate_size"], VISION["patch_size"]
    n_patches = (VISION["image_size"] // vp) ** 2

    def w(*shape):
        return rng.standard_normal(shape).astype(np.float32) * 0.05

    tensors = {
        "language_model.model.embed_tokens.weight": w(v, d),
        "language_model.model.norm.weight": np.ones((d,), np.float32),
        "language_model.lm_head.weight": w(v, d),
    }
    for i in range(TEXT["num_hidden_layers"]):
        lp = f"language_model.model.layers.{i}."
        tensors.update({
            lp + "input_layernorm.weight": np.ones((d,), np.float32),
            lp + "post_attention_layernorm.weight": np.ones((d,), np.float32),
            lp + "self_attn.q_proj.weight": w(d, d),
            lp + "self_attn.k_proj.weight": w(hkd, d),
            lp + "self_attn.v_proj.weight": w(hkd, d),
            lp + "self_attn.o_proj.weight": w(d, d),
            lp + "mlp.gate_proj.weight": w(f, d),
            lp + "mlp.up_proj.weight": w(f, d),
            lp + "mlp.down_proj.weight": w(d, f),
        })
    vt = "vision_tower.vision_model."
    tensors.update({
        vt + "embeddings.class_embedding": w(vd),
        vt + "embeddings.patch_embedding.weight": w(vd, 3, vp, vp),
        vt + "embeddings.position_embedding.weight": w(n_patches + 1, vd),
        vt + "pre_layrnorm.weight": np.ones((vd,), np.float32),
        vt + "pre_layrnorm.bias": np.zeros((vd,), np.float32),
        vt + "post_layernorm.weight": np.ones((vd,), np.float32),
        vt + "post_layernorm.bias": np.zeros((vd,), np.float32),
    })
    for i in range(VISION["num_hidden_layers"]):
        lp = f"{vt}encoder.layers.{i}."
        for proj in ("q_proj", "k_proj", "v_proj", "out_proj"):
            tensors[lp + f"self_attn.{proj}.weight"] = w(vd, vd)
            tensors[lp + f"self_attn.{proj}.bias"] = w(vd)
        tensors[lp + "layer_norm1.weight"] = np.ones((vd,), np.float32)
        tensors[lp + "layer_norm1.bias"] = np.zeros((vd,), np.float32)
        tensors[lp + "layer_norm2.weight"] = np.ones((vd,), np.float32)
        tensors[lp + "layer_norm2.bias"] = np.zeros((vd,), np.float32)
        tensors[lp + "mlp.fc1.weight"] = w(vf, vd)
        tensors[lp + "mlp.fc1.bias"] = w(vf)
        tensors[lp + "mlp.fc2.weight"] = w(vd, vf)
        tensors[lp + "mlp.fc2.bias"] = w(vd)
    tensors["multi_modal_projector.linear_1.weight"] = w(d, vd)
    tensors["multi_modal_projector.linear_1.bias"] = w(d)
    tensors["multi_modal_projector.linear_2.weight"] = w(d, d)
    tensors["multi_modal_projector.linear_2.bias"] = w(d)

    save_file(tensors, str(tmp_path / "model.safetensors"))
    with open(tmp_path / "config.json", "w") as fh:
        json.dump({
            "model_type": "llava",
            "image_token_index": 7,
            "text_config": TEXT,
            "vision_config": VISION,
        }, fh)
    return str(tmp_path)


def test_nested_text_config_resolves(vlm_dir):
    cfg = ModelConfig.from_dir(vlm_dir)
    assert cfg.model_type == "llama"
    assert cfg.hidden_size == TEXT["hidden_size"]
    assert cfg.vision_config["image_size"] == VISION["image_size"]
    assert cfg.image_token_index == 7


def test_language_weights_load_through_prefix(vlm_dir):
    from dynamo_tpu.models import loader

    cfg, params = loader.resolve_model(vlm_dir)
    assert params["embed"].shape == (TEXT["vocab_size"], TEXT["hidden_size"])
    # real (non-random) weights: embed matches the checkpoint
    from safetensors.numpy import load_file

    ckpt = load_file(os.path.join(vlm_dir, "model.safetensors"))
    np.testing.assert_allclose(
        np.asarray(params["embed"], np.float32),
        ckpt["language_model.model.embed_tokens.weight"],
        atol=1e-2,
    )


def test_vision_tower_loads_and_is_deterministic(vlm_dir):
    from dynamo_tpu.models.vision import encode_images, load_vision_hf

    vcfg, vparams = load_vision_hf(vlm_dir)
    assert vcfg.projection_dim == TEXT["hidden_size"]
    # class token participates: one extra position row, one fewer
    # transformer layer than the checkpoint (vision_feature_layer=-2)
    assert vparams["pos_embed"].shape == (vcfg.num_patches + 1, vcfg.hidden_size)
    assert vcfg.num_hidden_layers == VISION["num_hidden_layers"] - 1
    assert not vcfg.apply_post_ln
    rng = np.random.default_rng(1)
    pixels = rng.standard_normal(
        (1, VISION["image_size"], VISION["image_size"], 3)
    ).astype(np.float32)
    e1 = np.asarray(encode_images(vcfg, vparams, pixels), np.float32)
    e2 = np.asarray(encode_images(vcfg, vparams, pixels), np.float32)
    assert e1.shape == (1, vcfg.num_patches, TEXT["hidden_size"])
    np.testing.assert_array_equal(e1, e2)  # deterministic
    assert np.abs(e1).sum() > 0
    # different image -> different embeddings (weights actually loaded)
    e3 = np.asarray(
        encode_images(vcfg, vparams, pixels + 1.0), np.float32
    )
    assert np.abs(e1 - e3).max() > 1e-4


def test_cli_detects_vlm_checkpoint(vlm_dir, tmp_path):
    from dynamo_tpu.cli.main import _is_vlm_checkpoint

    assert _is_vlm_checkpoint(vlm_dir)
    plain = tmp_path / "plain"
    plain.mkdir()
    with open(plain / "config.json", "w") as f:
        json.dump(TEXT, f)
    assert not _is_vlm_checkpoint(str(plain))
    assert not _is_vlm_checkpoint(None)


async def test_vlm_engine_serves_with_real_embeddings(vlm_dir):
    """Full path: the engine loads the VLM's language weights; image
    embeddings from the REAL tower splice in via mm_embeds and change
    the greedy continuation vs text-only."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import JaxEngine
    from dynamo_tpu.models.vision import encode_images, load_vision_hf
    from dynamo_tpu.multimodal.embeds import pack_segments
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    vcfg, vparams = load_vision_hf(vlm_dir)
    rng = np.random.default_rng(2)
    pixels = rng.standard_normal(
        (1, VISION["image_size"], VISION["image_size"], 3)
    ).astype(np.float32)
    embeds = np.asarray(
        encode_images(vcfg, vparams, pixels), np.float32
    )[0]  # [n_patches, D]

    engine = await JaxEngine.launch(
        EngineConfig(
            model_path=vlm_dir, model_name="vlm", num_blocks=64,
            block_size=8, max_batch_size=4, prefill_chunk_size=32,
            max_model_len=128, decode_steps=2,
        )
    )
    try:
        n_img = embeds.shape[0]
        prompt = [1, 2] + [7] * n_img + [3, 4, 5]

        async def gen(rid, mm):
            req = PreprocessedRequest(
                request_id=rid, token_ids=list(prompt),
                sampling=SamplingOptions(use_greedy=True),
                stop=StopConditions(max_tokens=8),
                mm_embeds=pack_segments([(2, embeds)]) if mm else None,
            )
            toks = []
            async for item in engine.as_async_engine().generate(req, Context()):
                toks.extend(item.token_ids)
            return toks

        with_img = await gen("img", True)
        text_only = await gen("txt", False)
        assert len(with_img) == 8
        assert with_img != text_only  # the image actually conditioned it
        # deterministic across repeats
        assert await gen("img2", True) == with_img
    finally:
        await engine.shutdown()
